"""Batch tensorization: pods / nodepools / instance types → mask and
resource tensors.

Key architectural move vs the reference: pods are deduplicated into
**constraint signatures** first (a deployment's pods share nodeSelector/
affinity/tolerations — only resource sizes differ), so all host-side
set algebra is per-signature (S « P) and everything per-pod is a flat
numeric array. The reference re-runs its set algebra per pod per node
candidate (nodeclaim.go:65-119); we run it S×pools times, then the
pods×types math is pure tensor ops.

Resources are quantized per-resource to int32 (ceil for requests,
floor for allocatable) so packing sums are exact and never overpack.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..apis import labels as wk
from ..apis.nodepool import NodePool
from ..cloudprovider.types import InstanceType
from ..kube.objects import OP_DOES_NOT_EXIST, OP_NOT_IN, Pod
from ..kube.quantity import NANO
from ..scheduling import Requirement, Requirements, Taints, resources
from .stablehash import stable_hash
from ..scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    pod_requirements,
)
from ..tracing import tracer
from ..utils import pod as podutils
from .contracts import contract
from .vocab import Vocab

# canonical resource axis order; extras appended sorted
BASE_RESOURCES = ["cpu", "memory", "pods"]


def _is_neg(req: Requirement) -> bool:
    """Operator ∈ {NotIn, DoesNotExist} — the Intersects carve-out
    polarity (requirements.go:248-251)."""
    return req.operator() in (OP_NOT_IN, OP_DOES_NOT_EXIST)


@dataclass
class ResourceAxis:
    names: List[str]
    divisors: np.ndarray  # (R,) int64 per-resource quantization divisor

    @property
    def count(self) -> int:
        return len(self.names)

    def index(self, name: str) -> Optional[int]:
        try:
            return self.names.index(name)
        except ValueError:
            return None


def build_axis_from_capacities(capacities: Sequence[Dict[str, int]]) -> ResourceAxis:
    """Resource axis over arbitrary capacity dicts (instance types or
    existing nodes)."""
    names: Set[str] = set(BASE_RESOURCES)
    for cap in capacities:
        names.update(cap.keys())
    ordered = BASE_RESOURCES + sorted(names - set(BASE_RESOURCES))
    # per-resource divisor: keep the max value under 2^30 after division
    idx = {n: i for i, n in enumerate(ordered)}
    maxima = np.zeros(len(ordered), dtype=np.float64)
    for cap in capacities:
        for k, v in cap.items():
            i = idx[k]
            if v > maxima[i]:
                maxima[i] = v
    # divisors are 10^6 · 2^k (k ≥ 0): the quantized unit is a power-of-two
    # multiple of 1 milli, so whole-milli requests and capacities quantize
    # EXACTLY (ceil/floor agree with infinite precision) and exact-fit
    # packings survive quantization
    divisors = np.full(len(ordered), 10**6, dtype=np.int64)
    for i, m in enumerate(maxima):
        d = 10**6
        while m / d >= 2**30:
            d *= 2
        divisors[i] = d
    return ResourceAxis(ordered, divisors)


def build_catalog_axis(instance_types: Sequence[InstanceType]) -> ResourceAxis:
    """Resource axis determined by the catalog ALONE — stable across pod
    batches, which is what lets the encoded catalog be cached solve over
    solve. Pod-only extended resources are appended by ``extend_axis``;
    pod request magnitudes are handled by clamping (quantized requests
    saturate at 2^30, far above any capacity, so an oversized pod still
    reads as unschedulable)."""
    return build_axis_from_capacities([it.capacity for it in instance_types])


def extend_axis(
    axis: ResourceAxis, pods_requests: Sequence[Dict[str, int]]
) -> ResourceAxis:
    """Append pod-only resource names after the catalog columns (cached
    catalog tensors keep their column positions; their missing columns
    read as zero capacity, i.e. unschedulable — the reference's fits
    semantics for unregistered extended resources)."""
    known = set(axis.names)
    extra: Set[str] = set()
    for r in pods_requests:
        for k in r.keys():
            if k not in known:
                extra.add(k)
    if not extra:
        return axis
    return ResourceAxis(
        axis.names + sorted(extra),
        np.concatenate([axis.divisors, np.full(len(extra), 10**6, dtype=np.int64)]),
    )


def build_resource_axis(
    pods_requests: Sequence[Dict[str, int]], instance_types: Sequence[InstanceType]
) -> ResourceAxis:
    return extend_axis(build_catalog_axis(instance_types), pods_requests)


@contract(None, None, out="P R", eval_shape=False)
def build_requests_matrix(all_requests: Sequence[Dict[str, int]], axis: ResourceAxis) -> np.ndarray:
    """(P, R) int32 ceil-quantized request matrix — one python pass to a
    milli-unit float64 matrix (exact: values < 2^53), then vectorized
    power-of-two ceil-division. Sub-milli request precision is floored
    (harmless: real requests are whole milli-units)."""
    P = len(all_requests)
    name_to_idx = {n: i for i, n in enumerate(axis.names)}
    milli = np.zeros((P, axis.count), dtype=np.float64)
    for p, requests in enumerate(all_requests):
        row = milli[p]
        for k, v in requests.items():
            i = name_to_idx.get(k)
            if i is not None:
                row[i] = -(-v // 10**6)  # ceil: never let a pod look smaller
    # axis divisors are nano-scale powers of two ≥ 2^20 in the large case;
    # convert to milli-scale (may drop below 1 → clamp). Quantized values
    # saturate at 2^30: beyond every capacity, so still unschedulable.
    div = np.maximum(axis.divisors.astype(np.float64) / 10**6, 1.0)
    return np.minimum(np.ceil(milli / div[None, :]), 2.0**30).astype(np.int32)


@contract("P", None, None, out="P R", eval_shape=False)
def build_requests_matrix_ids(
    req_ids: np.ndarray, axis: ResourceAxis, id_to_req: Dict[int, Dict[str, int]]
) -> np.ndarray:
    """(P, R) int32 request matrix from interned request ids (podcache):
    quantize each *unique* request shape once, then gather — the 50k-pod
    batch usually has a few dozen distinct request rows. ``id_to_req``
    is the batch's own id→dict view (from its memos), so a concurrent
    intern-table reset cannot orphan this batch's ids."""
    if req_ids.size == 0:
        return np.zeros((0, axis.count), dtype=np.int32)
    uniq, inv = np.unique(req_ids, return_inverse=True)
    rows = build_requests_matrix([id_to_req[int(u)] for u in uniq], axis)
    return rows[inv]


def unique_requests(
    req_ids: np.ndarray, id_to_req: Dict[int, Dict[str, int]]
) -> List[Dict[str, int]]:
    """The distinct request dicts behind a batch's interned ids."""
    return [id_to_req[int(u)] for u in np.unique(req_ids)]


@contract(None, None, out="R", eval_shape=False)
def quantize_requests(requests: Dict[str, int], axis: ResourceAxis) -> np.ndarray:
    """ceil-quantize a request ResourceList → int32 vector (conservative:
    never lets a pod look smaller)."""
    out = np.zeros(axis.count, dtype=np.int64)
    for k, v in requests.items():
        i = axis.index(k)
        if i is not None:
            # python-int division: nanos can exceed int64 after ×; saturate
            # at 2^30 (beyond every capacity) so the result fits int32
            out[i] = min(-(-int(v) // int(axis.divisors[i])), 2**30)
    return out.astype(np.int32)


@contract(None, None, out="R", eval_shape=False)
def quantize_capacity(capacity: Dict[str, int], axis: ResourceAxis) -> np.ndarray:
    """floor-quantize an allocatable ResourceList (conservative: never lets
    a node look bigger). Saturates at 2^30 - 1: an axis built from a
    smaller capacity population (e.g. the consolidation repack's
    candidate-only axis) can meet a larger fleet node, and a bare int32
    cast would wrap its capacity negative — silently zeroing it. One
    below the request-side clamp so a saturated REQUEST (2^30, 'beyond
    every capacity') still never fits a saturated capacity."""
    out = np.zeros(axis.count, dtype=np.int64)
    for k, v in capacity.items():
        i = axis.index(k)
        if i is not None:
            out[i] = min(max(int(v), 0) // int(axis.divisors[i]), 2**30 - 1)
    return out.astype(np.int32)


# ---------------------------------------------------------------------------
# instance-type encoding


@dataclass
class EncodedInstanceTypes:
    """Per-NodePool tensor view of the catalog."""

    instance_types: List[InstanceType]
    axis: ResourceAxis
    allocatable: np.ndarray  # (T, R) int32, quantized
    prices: np.ndarray  # (T,) f64 — cheapest available offering price
    # per-key requirement masks, ragged over keys:
    key_masks: Dict[str, np.ndarray]  # key → (T, Vk) bool
    key_has: Dict[str, np.ndarray]  # key → (T,) bool
    key_neg: Dict[str, np.ndarray]  # key → (T,) bool
    # offering availability: (T, Z, C) bool over zone/capacity-type vocabs
    zones: List[str]
    capacity_types: List[str]
    offering_avail: np.ndarray
    offering_price: np.ndarray  # (T, Z, C) f64 (inf where unavailable)
    # per key, the (type index, Requirement) pairs behind key_masks — kept
    # so cached masks can be re-extended when the vocab grows (see
    # extend_encoded_masks)
    key_reqs: Dict[str, list] = field(default_factory=dict)
    # cross-solve derived-tensor caches (pareto frontiers, daemon-adjusted
    # allocatable) — they live and die with the encoding, so cached
    # catalog entries keep them warm across solves
    runtime_caches: Dict[tuple, np.ndarray] = field(default_factory=dict)


def encode_instance_types(instance_types: List[InstanceType], axis: ResourceAxis, vocab: Vocab) -> EncodedInstanceTypes:
    """Tensorize one catalog (cold path: cached across solves by
    solver._catalog_entry; traced because a catalog-generation bump
    re-pays it inside a live solve)."""
    with tracer.span("encode.instance_types", types=len(instance_types)):
        return _encode_instance_types(instance_types, axis, vocab)


def _encode_instance_types(instance_types: List[InstanceType], axis: ResourceAxis, vocab: Vocab) -> EncodedInstanceTypes:
    T = len(instance_types)
    # observe all values first so vocab widths are final
    for it in instance_types:
        for req in it.requirements.values():
            vocab.observe_requirement(req)
    zones = sorted({o.zone for it in instance_types for o in it.offerings})
    capacity_types = sorted({o.capacity_type for it in instance_types for o in it.offerings})
    z_index = {z: i for i, z in enumerate(zones)}
    c_index = {c: i for i, c in enumerate(capacity_types)}

    allocatable = np.zeros((T, axis.count), dtype=np.int32)
    prices = np.full(T, np.inf)
    offering_avail = np.zeros((T, len(zones), len(capacity_types)), dtype=bool)
    offering_price = np.full((T, len(zones), len(capacity_types)), np.inf)
    keys = sorted({req.key for it in instance_types for req in it.requirements.values()})
    key_masks = {k: np.zeros((T, vocab.key_vocab(k).size), dtype=bool) for k in keys}
    key_has = {k: np.zeros(T, dtype=bool) for k in keys}
    key_neg = {k: np.zeros(T, dtype=bool) for k in keys}

    key_reqs: Dict[str, list] = {k: [] for k in keys}
    for t, it in enumerate(instance_types):
        allocatable[t] = quantize_capacity(it.allocatable(), axis)
        for o in it.offerings:
            if o.available:
                zi, ci = z_index[o.zone], c_index[o.capacity_type]
                offering_avail[t, zi, ci] = True
                offering_price[t, zi, ci] = o.price
                prices[t] = min(prices[t], o.price)
        for key, req in it.requirements.items():
            kv = vocab.key_vocab(key)
            key_masks[key][t] = vocab.encode_mask(req, kv.size)
            key_has[key][t] = True
            key_neg[key][t] = _is_neg(req)
            key_reqs[key].append((t, req))

    return EncodedInstanceTypes(
        instance_types=instance_types,
        axis=axis,
        allocatable=allocatable,
        prices=prices,
        key_masks=key_masks,
        key_has=key_has,
        key_neg=key_neg,
        zones=zones,
        capacity_types=capacity_types,
        offering_avail=offering_avail,
        offering_price=offering_price,
        key_reqs=key_reqs,
    )


def extend_encoded_masks(enc: EncodedInstanceTypes, vocab: Vocab) -> None:
    """Grow a cached encoding's masks to the vocab's current widths.

    New slots stand for values interned after the encoding was built
    (by later pod batches): an In-requirement never listed them (they
    would have been interned at build time) so its mask extends with
    False; complement requirements re-evaluate ``req.has`` so Gt/Lt
    bounds stay exact. OTHER sits at slot 0, so existing slots never
    move (vocab.py invariant)."""
    for key, mask in enc.key_masks.items():
        kv = vocab.key_vocab(key)
        new = kv.size
        old = mask.shape[1]
        if new <= old:
            continue
        padded = np.zeros((mask.shape[0], new), dtype=bool)
        padded[:, :old] = mask
        new_values = kv.values[old - 1 :]  # slot i (i≥1) ↔ values[i-1]
        for t, req in enc.key_reqs.get(key, ()):
            if req.complement:
                for j, v in enumerate(new_values):
                    padded[t, old + j] = req.has(v)
        enc.key_masks[key] = padded


# ---------------------------------------------------------------------------
# pod signatures


def _toleration_key(t) -> tuple:
    return (t.key, t.operator, t.value, t.effect)


def _selector_key(sel) -> tuple:
    if sel is None:
        return ()
    return sel.key()


def selector_label_keys(pods: Sequence[Pod]) -> Set[str]:
    """Label keys referenced by any topology-spread / affinity selector in
    the batch — the only labels that affect scheduling identity. One
    implementation: podcache's per-pod walk (memoized there)."""
    from .podcache import _selector_keys

    keys: Set[str] = set()
    for pod in pods:
        keys.update(_selector_keys(pod))
    return keys


def pod_signature(pod: Pod, relevant_label_keys: Optional[Set[str]] = None) -> tuple:
    """Constraint identity: pods with equal signatures are interchangeable
    for compat + topology purposes (resource sizes excluded). Only labels
    some selector in the batch can match participate — otherwise identical
    pods from different deployments would never share a node."""
    if relevant_label_keys is None:
        labels_key = tuple(sorted(pod.metadata.labels.items()))
    else:
        labels_key = tuple(
            sorted((k, v) for k, v in pod.metadata.labels.items() if k in relevant_label_keys)
        )
    # host ports and PVC-backed volumes are per-node stateful constraints
    # (hostportusage.go:70-90, volumeusage.go:79-178) — they join the
    # signature so port/volume-bearing pods never silently share a group
    # with unconstrained ones
    spec = pod.spec
    ports_key = tuple(
        sorted(
            (p.host_ip or "", p.host_port, p.protocol or "TCP")
            for c in spec.containers + spec.init_containers
            for p in c.ports
            if p.host_port
        )
    )
    volumes_key = tuple(
        sorted(
            (v.name, v.persistent_volume_claim or "", bool(v.ephemeral))
            for v in spec.volumes
            if v.persistent_volume_claim is not None or v.ephemeral
        )
    )
    # fast path: fully unconstrained pod (the common case at 50k scale)
    if (
        spec.affinity is None
        and not spec.node_selector
        and not spec.tolerations
        and not spec.topology_spread_constraints
        and not ports_key
        and not volumes_key
    ):
        return (pod.namespace, labels_key, (), (), (), (), (), ())
    spreads = tuple(
        (c.topology_key, c.max_skew, c.when_unsatisfiable, _selector_key(c.label_selector), c.min_domains)
        for c in pod.spec.topology_spread_constraints
    )
    aff = pod.spec.affinity
    node_aff_key: tuple = ()
    pod_aff_key: tuple = ()
    anti_aff_key: tuple = ()
    if aff is not None:
        if aff.node_affinity is not None:
            na = aff.node_affinity
            req_terms = (
                tuple(
                    tuple((e.key, e.operator, tuple(e.values)) for e in term.match_expressions)
                    for term in na.required.node_selector_terms
                )
                if na.required
                else ()
            )
            pref_terms = tuple(
                (p.weight, tuple((e.key, e.operator, tuple(e.values)) for e in p.preference.match_expressions))
                for p in na.preferred
            )
            node_aff_key = (req_terms, pref_terms)
        if aff.pod_affinity is not None:
            pod_aff_key = tuple(
                (t.topology_key, _selector_key(t.label_selector), tuple(t.namespaces))
                for t in aff.pod_affinity.required
            ) + tuple(
                (w.weight, w.pod_affinity_term.topology_key, _selector_key(w.pod_affinity_term.label_selector))
                for w in aff.pod_affinity.preferred
            )
        if aff.pod_anti_affinity is not None:
            anti_aff_key = tuple(
                (t.topology_key, _selector_key(t.label_selector), tuple(t.namespaces))
                for t in aff.pod_anti_affinity.required
            ) + tuple(
                (w.weight, w.pod_affinity_term.topology_key, _selector_key(w.pod_affinity_term.label_selector))
                for w in aff.pod_anti_affinity.preferred
            )
    return (
        pod.namespace,
        labels_key,
        tuple(sorted(pod.spec.node_selector.items())),
        tuple(sorted(_toleration_key(t) for t in pod.spec.tolerations)),
        spreads,
        node_aff_key,
        pod_aff_key,
        anti_aff_key,
        ports_key,
        volumes_key,
    )


@dataclass
class SignatureGroup:
    """Pods sharing one constraint signature."""

    signature: tuple
    exemplar: Pod
    pod_indices: List[int] = field(default_factory=list)  # into the batch array
    # interned signature id (podcache.intern_sig) — the cross-solve
    # compat/route cache key. None for ad-hoc groups (relaxation
    # retries), which bypass every incremental cache.
    sig_id: Optional[int] = None

    def _is_inns_term(self, term) -> bool:
        """Term scoped to the pod's own namespace (no namespace selector,
        namespaces empty or the pod's own) — cross-namespace scoping
        stays on the oracle."""
        if term.namespace_selector is not None:
            return False
        ns = list(term.namespaces)
        return not ns or ns == [self.exemplar.namespace]

    def _is_self_term(self, term) -> bool:
        """The term's selector matches the exemplar's own labels in its
        own namespace — the per-deployment co-location/isolation pattern
        that tensorizes (cross-selecting terms anchor to OTHER pods and
        need the oracle's global view)."""
        sel = term.label_selector
        if sel is None or not sel.matches(self.exemplar.metadata.labels):
            return False
        return self._is_inns_term(term)

    def tensor_affinity_terms(self) -> Optional[list]:
        """The group's REQUIRED pod-affinity terms when the whole set
        has the tensorizable shape (ISSUE 12: multi-term required
        affinity resolves post-pack by intersecting per-term domain
        masks): every term on zone/hostname, in-namespace, selector
        present, no preferred terms, no anti-affinity or spread mix —
        else None (oracle residue)."""
        a = self.exemplar.spec.affinity
        if a is None or a.pod_affinity is None:
            return None
        if a.pod_anti_affinity is not None:
            return None  # affinity+anti interactions stay on the oracle
        if self.exemplar.spec.topology_spread_constraints:
            return None  # affinity+spread interactions stay on the oracle
        if a.pod_affinity.preferred or not a.pod_affinity.required:
            return None
        hostname_terms = 0
        for term in a.pod_affinity.required:
            if term.topology_key not in (wk.LABEL_TOPOLOGY_ZONE, wk.LABEL_HOSTNAME):
                return None
            if term.topology_key == wk.LABEL_HOSTNAME:
                hostname_terms += 1
            if term.label_selector is None:
                # nil selector semantics differ between worlds (the
                # reference treats it as match-nothing) — oracle
                return None
            if not self._is_inns_term(term):
                return None
        if hostname_terms > 1:
            # two host-scoped terms can interleave anchored and
            # bootstrap states mid-group (each placement re-anchors the
            # other term) — that walk stays on the oracle
            return None
        return list(a.pod_affinity.required)

    def tensor_pod_affinity(self) -> Optional[str]:
        """Primary topology key of the tensorizable required affinity
        terms: LABEL_HOSTNAME when any term is host-scoped (the hostname
        post-pass zone-filters through the zone terms), else
        LABEL_TOPOLOGY_ZONE; None when the shape stays on the oracle."""
        terms = self.tensor_affinity_terms()
        if terms is None:
            return None
        if any(t.topology_key == wk.LABEL_HOSTNAME for t in terms):
            return wk.LABEL_HOSTNAME
        return wk.LABEL_TOPOLOGY_ZONE

    def affinity_terms(self) -> list:
        """The required pod-affinity terms behind tensor_pod_affinity
        (call only when it returned a key)."""
        return list(self.exemplar.spec.affinity.pod_affinity.required)

    def affinity_term(self):
        """First required pod-affinity term (single-term callers)."""
        return self.exemplar.spec.affinity.pod_affinity.required[0]

    def affinity_self_selecting(self) -> bool:
        """Whether the group's pods match EVERY one of their own
        affinity selectors — gates the bootstrap-one-domain rule
        (topologygroup.go:226-232: only self-selecting pods may seed an
        empty domain; with multiple terms, every anchor-less term must
        be seedable by the pod itself)."""
        return all(
            t.label_selector is None
            or t.label_selector.matches(self.exemplar.metadata.labels)
            for t in self.affinity_terms()
        )

    def self_pod_affinity(self) -> Optional[str]:
        """Topology key of a single self-selecting REQUIRED pod-affinity
        term on zone/hostname (co-locate a deployment with itself), when
        that is the group's only affinity shape — else None."""
        key = self.tensor_pod_affinity()
        if key is None or not self._is_self_term(self.affinity_term()):
            return None
        return key

    @property
    def zone_anti_isolated(self) -> bool:
        """Required self-anti-affinity on zone → at most one pod of the
        group per zone."""
        a = self.exemplar.spec.affinity
        if a is None or a.pod_anti_affinity is None:
            return False
        for term in a.pod_anti_affinity.required:
            if term.topology_key == wk.LABEL_TOPOLOGY_ZONE and self._is_self_term(term):
                return True
        return False

    def tensor_anti_terms(self) -> Optional[list]:
        """The group's REQUIRED anti-affinity terms when the whole set
        tensorizes (ISSUE 12): every term on zone/hostname and
        in-namespace, no preferred terms, no pod-affinity mix. Self
        terms keep the pods-per-domain=1 paths; non-self terms become
        static domain-exclusion masks from the seeded counts (the
        routing layer additionally sends any group whose term selector
        matches another BATCH group to the oracle — in-batch counted
        placements need the oracle's interleaving, topology.go:190-219).
        Spread mix: allowed only for the historical hostname-self shape
        (max_per_node composes); anything else stays on the oracle.
        Nil-selector terms match nothing (metav1 semantics) and ride
        along as no-ops."""
        a = self.exemplar.spec.affinity
        if a is None or a.pod_anti_affinity is None:
            return None
        if a.pod_anti_affinity.preferred:
            return None
        if a.pod_affinity is not None:
            return None  # affinity+anti interactions stay on the oracle
        req = list(a.pod_anti_affinity.required)
        if not req:
            return None
        for term in req:
            if term.topology_key not in (wk.LABEL_TOPOLOGY_ZONE, wk.LABEL_HOSTNAME):
                return None
            if term.label_selector is not None and not self._is_inns_term(term):
                return None
        if self.exemplar.spec.topology_spread_constraints and not all(
            t.topology_key == wk.LABEL_HOSTNAME and self._is_self_term(t)
            for t in req
            if t.label_selector is not None
        ):
            return None  # only hostname-self anti composes with spread
        return req

    def anti_exclusion_terms(self) -> list:
        """Non-self tensor anti terms (selector anchors to OTHER pods):
        the domain-exclusion mask inputs. Empty when none tensorize."""
        terms = self.tensor_anti_terms()
        if terms is None:
            return []
        return [
            t
            for t in terms
            if t.label_selector is not None and not self._is_self_term(t)
        ]

    @property
    def has_relational(self) -> bool:
        """Pod affinity/anti-affinity needs the oracle (SURVEY §7 hard
        parts) — except the shapes that tensorize: required anti-
        affinity on zone/hostname (self terms → pods-per-domain=1,
        non-self terms → seeded domain-exclusion masks, ISSUE 12) and
        multi-term required affinity on zone/hostname (post-pack
        intersected anchor masks)."""
        a = self.exemplar.spec.affinity
        if a is None:
            return False
        if a.pod_affinity is not None and (a.pod_affinity.required or a.pod_affinity.preferred):
            if self.tensor_affinity_terms() is None:
                return True
        if a.pod_anti_affinity is not None:
            if self.tensor_anti_terms() is None:
                return True
        return False

    @property
    def has_relational_legacy(self) -> bool:
        """The pre-ISSUE-12 routing predicate, kept verbatim as the
        KARPENTER_TPU_CONSTRAINT_ENGINE=oracle identity reference:
        only self-selecting single-term shapes tensorize."""
        a = self.exemplar.spec.affinity
        if a is None:
            return False
        if a.pod_affinity is not None and (a.pod_affinity.required or a.pod_affinity.preferred):
            terms = self.tensor_affinity_terms()
            if terms is None or len(terms) != 1:
                return True
        if a.pod_anti_affinity is not None:
            req = a.pod_anti_affinity.required
            if a.pod_anti_affinity.preferred:
                return True
            for term in req:
                if term.topology_key == wk.LABEL_HOSTNAME and self._is_self_term(term):
                    continue  # tensorizes as pods-per-node=1
                if (
                    term.topology_key == wk.LABEL_TOPOLOGY_ZONE
                    and self._is_self_term(term)
                    and not self.exemplar.spec.topology_spread_constraints
                ):
                    continue  # tensorizes as pods-per-zone=1 (no spread mix)
                return True  # anti-affinity against other pods — relational
        return False

    @property
    def has_stateful_node_constraints(self) -> bool:
        """Host ports / PVC volumes carry per-node conflict state
        (hostportusage.go:70, volumeusage.go:79). ISSUE 12 folds both
        into the pack scan (port feature axes, volume admit masks) for
        topology-free groups; see tensor_stateful."""
        spec = self.exemplar.spec
        for c in spec.containers + spec.init_containers:
            for p in c.ports:
                if p.host_port:
                    return True
        for v in spec.volumes:
            if v.persistent_volume_claim is not None or v.ephemeral:
                return True
        return False

    @property
    def tensor_stateful(self) -> bool:
        """Stateful (port/volume) group whose shape the tensor path
        covers: no pod affinity/anti-affinity and no topology spread —
        stateful × topology combinations remain oracle residue."""
        if not self.has_stateful_node_constraints:
            return False
        spec = self.exemplar.spec
        if spec.topology_spread_constraints:
            return False
        a = spec.affinity
        return a is None or (a.pod_affinity is None and a.pod_anti_affinity is None)

    def host_ports(self) -> tuple:
        """Canonical (protocol, port, ip) triples of the group's host
        ports (identical across members — ports ride the signature)."""
        from .constraint_tensors import canonical_ports

        return canonical_ports(self.exemplar)

    @property
    def has_volumes(self) -> bool:
        return any(
            v.persistent_volume_claim is not None or v.ephemeral
            for v in self.exemplar.spec.volumes
        )

    @property
    def hostname_isolated(self) -> bool:
        """Required self-anti-affinity on hostname → one pod per node."""
        a = self.exemplar.spec.affinity
        if a is None or a.pod_anti_affinity is None:
            return False
        return any(
            term.topology_key == wk.LABEL_HOSTNAME and self._is_self_term(term)
            for term in a.pod_anti_affinity.required
        )

    def zone_spread(self):
        """The zone topology-spread constraint, if any."""
        for c in self.exemplar.spec.topology_spread_constraints:
            if c.topology_key == wk.LABEL_TOPOLOGY_ZONE:
                return c
        return None

    def hostname_spread(self):
        for c in self.exemplar.spec.topology_spread_constraints:
            if c.topology_key == wk.LABEL_HOSTNAME:
                return c
        return None


def group_pods(pods: List[Pod], memos=None) -> List[SignatureGroup]:
    """Signature-group the batch. Signatures are memoized per pod
    (podcache), revalidated against the batch's relevant-label-key set:
    two batches with different selector populations filter different
    label subsets into the signature, so the memo carries the
    fingerprint it was computed under."""
    from . import podcache

    if memos is None:
        memos = podcache.get_memos(pods)
    relevant: Set[str] = set()
    for m in memos:
        if m.selector_keys:
            relevant.update(m.selector_keys)
    # process-stable digest (NOT builtin hash: the relevant-label
    # fingerprint rides in pod memos that the bench's restart-shaped
    # cold solver must reproduce bit-identically under any hash seed)
    fp = stable_hash(tuple(sorted(relevant)))
    groups: Dict[int, SignatureGroup] = {}
    get = groups.get
    for i, (pod, m) in enumerate(zip(pods, memos)):
        # read/write sig_state as one atomic reference; use LOCALS for
        # grouping so a concurrent group_pods (different fingerprint, e.g.
        # a disruption simulation) can overwrite the memo without this
        # batch mixing the two fingerprints' signatures
        state = m.sig_state
        if state is None or state[0] != fp:
            sig = pod_signature(pod, relevant)
            state = (fp, sig, podcache.intern_sig(sig))
            m.sig_state = state
        g = get(state[2])
        if g is None:
            g = SignatureGroup(signature=state[1], exemplar=pod, sig_id=state[2])
            groups[state[2]] = g
        g.pod_indices.append(i)
    return list(groups.values())


# ---------------------------------------------------------------------------
# signature × pool compatibility (host-side set algebra, S×pools small)


@dataclass
class PoolEncoding:
    nodepool: NodePool
    template_requirements: Requirements
    taints: Taints


@dataclass
class SignaturePoolCompat:
    """Host-side verdicts + merged requirement masks for one (signature,
    pool) pair; feeds the instance-type compat kernel."""

    compatible: bool  # pod vs template (taints + Compatible w/ well-known)
    error: str = ""
    # merged (template ∩ pod) requirement encoding, per key:
    key_mask: Dict[str, np.ndarray] = field(default_factory=dict)  # key → (Vk,) bool
    key_has: Dict[str, bool] = field(default_factory=dict)
    key_neg: Dict[str, bool] = field(default_factory=dict)
    merged: Optional[Requirements] = None


def encode_signature_for_pool(
    group: SignatureGroup, pool: PoolEncoding, vocab: Vocab
) -> SignaturePoolCompat:
    """The oracle's per-pod template checks, once per signature
    (nodeclaim.go:65-101 minus topology)."""
    pod = group.exemplar
    err = pool.taints.tolerates(pod)
    if err:
        return SignaturePoolCompat(False, err)
    pod_reqs = pod_requirements(pod)
    err = pool.template_requirements.compatible(pod_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
    if err:
        return SignaturePoolCompat(False, f"incompatible requirements, {err}")
    merged = Requirements(*pool.template_requirements.values_list())
    merged.add(*pod_reqs.values_list())
    out = SignaturePoolCompat(True, merged=merged)
    for key, req in merged.items():
        for v in req.values:
            vocab.key_vocab(key).intern(v)
        out.key_has[key] = True
        out.key_neg[key] = _is_neg(req)
        out.key_mask[key] = req  # mask encoded later, after vocab is final
    return out


def finalize_signature_masks(compats: List[SignaturePoolCompat], vocab: Vocab) -> None:
    """Second pass: encode masks once every value has been interned."""
    for c in compats:
        if not c.compatible:
            continue
        for key, req in list(c.key_mask.items()):
            if isinstance(req, Requirement):
                c.key_mask[key] = vocab.encode_mask(req, vocab.key_vocab(key).size)
