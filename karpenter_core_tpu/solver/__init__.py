"""The TPU scheduling core.

No Go analogue — this replaces the reference's per-pod greedy hot loop
(pkg/controllers/provisioning/scheduling/scheduler.go:140-285) with a
batched JAX pipeline:

1. ``vocab``/``encode``: requirements → boolean masks over per-key
   value vocabularies; resources → fixed-width f32 matrices.
2. ``kernels``: the pods×types compatibility kernel (per-key MXU
   matmuls) and resource-fit masks — the tensorized equivalent of
   ``filterInstanceTypesByRequirements`` (nodeclaim.go:225).
3. ``pack``: K-open-node first-fit-decreasing as a ``lax.scan``,
   vmapped over constraint-signature groups; cheapest-type assignment.
4. ``merge``: the bucketed, vectorized cross-group merge engine
   (``KARPENTER_TPU_MERGE_ENGINE`` selects vector vs the scalar
   reference loop; both are plan-identical by construction and test).
5. ``solver``: the end-to-end TPUScheduler with CPU-oracle fallback for
   relational constraints (pod affinity) and parity metrics.
"""

from .solver import TPUScheduler, SolverResult
