"""Concurrency-soundness rules (ISSUE 18): the proof plane over the
repo's lock graph.

Three project-level rules share one cross-module analysis pass:

``lock-order``
    Discovers every Lock/RLock/Condition the scanned modules create
    (``self.X = threading.Lock()`` fields and module-level globals),
    computes the may-hold set at each acquire site — lexically inside a
    function, and across calls via a "caller holds the lock" call-graph
    fixpoint extended cross-module — and builds the global lock-order
    graph. Cycles are potential deadlocks; a 2-cycle is the classic
    inconsistent-order pair. Locks classified as *sinks* (observability
    / interning leaves, see ``_SINK_MODULES`` / ``_SINK_LOCK_IDS``) are
    statically VERIFIED to be leaves: a sink acquiring a non-sink lock
    is itself a finding, and edges *into* sinks are allowed because a
    verified leaf cannot close a cycle.

``wait-under-lock``
    Flags blocking operations executed while a discovered (non-sink)
    lock is held: ``time.sleep``, file I/O (``open``/``pickle.dump``),
    ``subprocess``, device dispatch through the deviceplane seam
    (``pack_jobs`` / ``warmup_compile_only``), queue handoffs on
    StageQueue/Queue-typed receivers, thread ``join()``, ``Event.wait``
    and ``Condition.wait`` on a *different* lock — both directly and
    through resolved calls (the may-block fixpoint). The no-timeout
    sub-check flags zero-argument ``join()`` / ``Event.wait()``
    anywhere in the scanned modules: bounded waits with counted
    timeout outcomes, never silent hangs. Justified handoff sites use
    the scoped marker ``# analysis: allow-wait-under-lock(<why>)`` —
    the argument IS the soundness argument, a bare marker is not
    accepted by review.

``process-boundary``
    Values reachable from a serialization boundary (warmstore payload
    builders, anything feeding ``pickle.dump``/``write_snapshot``,
    ``__getstate__``) must be content-addressed: no ``id()``, no
    threading primitives, no open handles, and no process-local
    interned ordinals. The ordinal check is taint-based: a name passed
    to a ``sig_for_id()`` translator (``sig_names.get(sid)``) is by
    construction a process ordinal — storing that *name* (rather than
    its translated content) into the payload reach is the bug. This is
    the ROADMAP item-1 prerequisite: the emit-side twin of the
    cache-persist restore rules.

The module also exports the runtime witness surface
(``witness_inventory`` / ``static_order_graph``) consumed by
``analysis/lockwitness.py``: the conftest-gated instrumentation that
records actual acquisition orders across tier-1 and asserts every
observed edge is present in the static graph.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import (
    DEFAULT_CONFIG,
    FileContext,
    ProjectContext,
    dotted_name,
    project_rule,
    repo_root,
)
from .findings import Finding, scoped_marker_args

# ---------------------------------------------------------------------------
# classification

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
}

#: Modules whose locks are observability/interning leaves by contract:
#: they guard a counter bump, a ring append, or an intern table, and
#: must never acquire coordination locks. The lock-order rule VERIFIES
#: that (a sink acquiring a non-sink lock is a finding); in exchange,
#: edges into sinks are allowed (a verified leaf cannot close a cycle),
#: sinks are excluded from wait-under-lock held-sets (single-flight
#: profile gates sleep while held, by design), and the runtime witness
#: does not instrument them (metric bumps under a Condition are
#: statically invisible but provably harmless).
_SINK_MODULES = (
    "karpenter_core_tpu/metrics/registry.py",
    "karpenter_core_tpu/tracing/tracer.py",
    "karpenter_core_tpu/tracing/flightrec.py",
    "karpenter_core_tpu/tracing/deviceplane.py",
    "karpenter_core_tpu/events/recorder.py",
    "karpenter_core_tpu/utils/atomic.py",
    "karpenter_core_tpu/serving/latency.py",
    "karpenter_core_tpu/solver/podcache.py",
    "karpenter_core_tpu/native/__init__.py",
    "karpenter_core_tpu/kube/faults.py",
    "karpenter_core_tpu/operator/server.py",
)

#: Per-lock sink membership for modules that mix coordination locks
#: with leaf locks (incremental.py holds both WarmState.lock — a
#: coordination lock — and the internally-synchronized LRU._mu leaf).
_SINK_LOCK_IDS = (
    "karpenter_core_tpu/solver/incremental.py::LRU._mu",
    "karpenter_core_tpu/solver/warmstore.py::_LAST_LOCK",
)

#: Deliberately small device-dispatch seam: calls that commute work to
#: the accelerator. Encode-kernel calls under _CATALOG_LOCK are the
#: catalog entry's documented mutation contract and stay out of this
#: set (residual assumption, see RULES.md).
_DEVICE_SEAM = {"pack_jobs", "warmup_compile_only"}

_QUEUE_BLOCKERS = {"put", "get", "get_entry"}
_QUEUE_CTOR_SUFFIXES = ("StageQueue", "Queue", "SimpleQueue")
_EVENT_CTOR_SUFFIXES = ("Event",)
_REACH_MUTATORS = {"append", "extend", "add", "insert", "update", "setdefault"}

#: Block kinds that propagate through the call graph into
#: wait-under-lock findings at the holding call site. Timed parking
#: ("wait"/"queue" with a timeout) stays a direct-site-only concern —
#: propagating it would flag every lock that ever calls into a
#: backpressure seam.
_PROPAGATED_KINDS = ("device", "io", "join", "sleep", "subprocess")

_SERIALIZER_NAMES = {"write_snapshot", "dump", "dumps"}

WAIT_RULE = "wait-under-lock"


def _is_sink(lock_id: str, relpath: str) -> bool:
    """Sink classification with suffix tolerance so fixture copies
    (bare filenames in a tmp tree) classify like their originals."""
    for s in _SINK_LOCK_IDS:
        if lock_id == s or s.endswith("/" + lock_id):
            return True
    for m in _SINK_MODULES:
        if relpath == m or m.endswith("/" + relpath):
            return True
    return False


# ---------------------------------------------------------------------------
# model


@dataclass
class LockDef:
    lock_id: str  # "relpath::Class.attr" or "relpath::NAME"
    relpath: str
    line: int  # line of the threading.<ctor>() call (creation site)
    kind: str  # Lock | RLock | Condition
    cls: str  # owning class name, "" for module-level
    attr: str
    sink: bool


@dataclass
class _ModInfo:
    ctx: FileContext
    relpath: str
    imports: Dict[str, str] = field(default_factory=dict)  # name -> module relpath
    from_names: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    functions: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    module_locks: Dict[str, str] = field(default_factory=dict)  # NAME -> lock_id


@dataclass
class _Acquire:
    lock_id: str
    line: int
    held: Tuple[str, ...]
    fnkey: Tuple[str, str]


@dataclass
class _Block:
    kind: str
    line: int
    desc: str
    held: Tuple[str, ...]
    fnkey: Tuple[str, str]
    untimed: bool = False
    own_lock: str = ""  # for cv-wait: the lock the wait releases


@dataclass
class _CallSite:
    callee: Tuple[str, str]
    line: int
    desc: str
    held: Tuple[str, ...]
    fnkey: Tuple[str, str]


@dataclass
class _FnSummary:
    fnkey: Tuple[str, str]  # (relpath, qualname)
    acquires: List[_Acquire] = field(default_factory=list)
    blocks: List[_Block] = field(default_factory=list)
    calls: List[_CallSite] = field(default_factory=list)


# ---------------------------------------------------------------------------
# the analyzer


class _Analyzer:
    def __init__(self, pctx: ProjectContext) -> None:
        self.pctx = pctx
        self.mods: Dict[str, _ModInfo] = {}
        self.locks: Dict[str, LockDef] = {}
        # (relpath, cls) -> attr -> lock_id
        self.class_locks: Dict[Tuple[str, str], Dict[str, str]] = {}
        # (relpath, cls) -> attr -> ("class",(rel,cls)) | ("event",) | ("queue",)
        self.class_fields: Dict[Tuple[str, str], Dict[str, tuple]] = {}
        self.class_bases: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        self.fn_defs: Dict[Tuple[str, str], ast.FunctionDef] = {}
        self.summaries: Dict[Tuple[str, str], _FnSummary] = {}
        self.may_acquire: Dict[Tuple[str, str], Set[str]] = {}
        self.may_block: Dict[Tuple[str, str], Set[str]] = {}
        # (src,dst) -> sorted sites [(relpath, line, qualname)]
        self.edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        # base class -> direct subclasses (inverse of class_bases);
        # built lazily once scanning is complete
        self._children: Optional[Dict[Tuple[str, str], List[Tuple[str, str]]]] = None
        self._targets_cache: Dict[Tuple[str, str], Tuple[Tuple[str, str], ...]] = {}

    # -- module set -------------------------------------------------------

    def scan_files(self) -> List[FileContext]:
        suffixes = list(self.pctx.config.concurrency_modules)
        return self.pctx.matching(suffixes)

    def run(self) -> None:
        files = self.scan_files()
        for ctx in files:
            self._index_module(ctx)
        for rel in sorted(self.mods):
            self._discover_locks(self.mods[rel])
        for rel in sorted(self.mods):
            self._infer_fields(self.mods[rel])
        for rel in sorted(self.mods):
            self._scan_module(self.mods[rel])
        self._fixpoints()
        self._build_edges()

    # -- indexing ---------------------------------------------------------

    def _module_relpath(self, dotted_mod: str) -> Optional[str]:
        base = dotted_mod.replace(".", "/")
        for cand in (base + ".py", base + "/__init__.py"):
            if cand in self.mods or self.pctx.get(cand) is not None:
                return cand
        return None

    def _index_module(self, ctx: FileContext) -> None:
        if ctx.relpath in self.mods:
            return
        mi = _ModInfo(ctx=ctx, relpath=ctx.relpath)
        self.mods[ctx.relpath] = mi
        pkg_parts = ctx.relpath.split("/")[:-1]
        # imports anywhere in the file (function-level imports hoisted:
        # registry.add_tenant does `from ..solver import prewarm as ...`)
        for node in ctx.walk():
            if isinstance(node, ast.Import):
                for alias in node.names:
                    rel = self._module_relpath(alias.name)
                    if rel is not None:
                        mi.imports[alias.asname or alias.name.split(".")[0]] = rel
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    up = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    if node.level - 1 > len(pkg_parts):
                        continue
                    base = "/".join(up + (node.module or "").split("."))
                    base = base.strip("/").replace("//", "/")
                    dotted_mod = base.replace("/", ".")
                else:
                    dotted_mod = node.module or ""
                target = self._module_relpath(dotted_mod) if dotted_mod else None
                if target is None:
                    continue
                for alias in node.names:
                    # `from ..solver import prewarm` may name a submodule
                    sub = self._module_relpath(dotted_mod + "." + alias.name)
                    if sub is not None and not self._defines(target, alias.name):
                        mi.imports[alias.asname or alias.name] = sub
                    else:
                        mi.from_names[alias.asname or alias.name] = (target, alias.name)
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                mi.classes[node.name] = node
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi.functions[node.name] = node

    def _defines(self, relpath: str, name: str) -> bool:
        ctx = self.pctx.get(relpath)
        if ctx is None:
            return False
        for node in ctx.tree.body:
            if isinstance(node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == name:
                    return True
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return True
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if (alias.asname or alias.name) == name:
                        return True
        return False

    def _ensure_module(self, relpath: str) -> Optional[_ModInfo]:
        if relpath in self.mods:
            return self.mods[relpath]
        ctx = self.pctx.get(relpath)
        if ctx is None:
            return None
        self._index_module(ctx)
        mi = self.mods[relpath]
        self._discover_locks(mi)
        self._infer_fields(mi)
        return mi

    def _resolve_class(
        self, mi: _ModInfo, name: str, depth: int = 0
    ) -> Optional[Tuple[str, str]]:
        """Class name in a module's scope -> (relpath, classname), chasing
        `from x import C` and package ``__init__`` re-exports (depth<=3)."""
        if depth > 3 or not name:
            return None
        head, _, tail = name.partition(".")
        if tail:  # mod.Class via a module import
            target = mi.imports.get(head)
            if target is not None:
                tm = self._ensure_module(target)
                if tm is not None:
                    return self._resolve_class(tm, tail, depth + 1)
            return None
        if name in mi.classes:
            return (mi.relpath, name)
        hit = mi.from_names.get(name)
        if hit is not None:
            target, orig = hit
            tm = self._ensure_module(target)
            if tm is not None:
                return self._resolve_class(tm, orig, depth + 1)
        return None

    def _resolve_function(
        self, mi: _ModInfo, name: str, depth: int = 0
    ) -> Optional[Tuple[str, str]]:
        if depth > 3 or not name:
            return None
        head, _, tail = name.partition(".")
        if tail:
            target = mi.imports.get(head)
            if target is not None:
                tm = self._ensure_module(target)
                if tm is not None:
                    return self._resolve_function(tm, tail, depth + 1)
            return None
        if name in mi.functions:
            return (mi.relpath, name)
        hit = mi.from_names.get(name)
        if hit is not None:
            target, orig = hit
            tm = self._ensure_module(target)
            if tm is not None:
                return self._resolve_function(tm, orig, depth + 1)
        return None

    # -- lock + field discovery ------------------------------------------

    def _lock_ctor_kind(self, node: ast.AST) -> Optional[Tuple[str, int]]:
        if not isinstance(node, ast.Call):
            return None
        kind = _LOCK_CTORS.get(dotted_name(node.func))
        if kind is None:
            return None
        return kind, node.lineno

    def _add_lock(self, relpath: str, cls: str, attr: str, kind: str, line: int) -> str:
        lock_id = f"{relpath}::{cls}.{attr}" if cls else f"{relpath}::{attr}"
        sink = _is_sink(lock_id, relpath)
        self.locks[lock_id] = LockDef(lock_id, relpath, line, kind, cls, attr, sink)
        return lock_id

    def _discover_locks(self, mi: _ModInfo) -> None:
        def module_stmts(body):
            for stmt in body:
                yield stmt
                if isinstance(stmt, (ast.If, ast.Try)):
                    for sub in ast.iter_child_nodes(stmt):
                        if isinstance(sub, ast.stmt):
                            yield from module_stmts([sub])

        for stmt in module_stmts(mi.ctx.tree.body):
            targets: List[ast.expr] = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if value is None:
                continue
            hit = self._lock_ctor_kind(value)
            if hit is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    mi.module_locks[t.id] = self._add_lock(
                        mi.relpath, "", t.id, hit[0], hit[1]
                    )
        for cname, cdef in mi.classes.items():
            key = (mi.relpath, cname)
            self.class_locks.setdefault(key, {})
            for meth in cdef.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for node in ast.walk(meth):
                    if not isinstance(node, ast.Assign):
                        continue
                    hit = self._lock_ctor_kind(node.value)
                    if hit is None:
                        continue
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            self.class_locks[key][t.attr] = self._add_lock(
                                mi.relpath, cname, t.attr, hit[0], hit[1]
                            )

    def _infer_fields(self, mi: _ModInfo) -> None:
        for cname, cdef in mi.classes.items():
            key = (mi.relpath, cname)
            if key in self.class_fields:
                continue
            fields: Dict[str, tuple] = {}
            self.class_fields[key] = fields
            bases: List[Tuple[str, str]] = []
            for b in cdef.bases:
                bk = self._resolve_class(mi, dotted_name(b))
                if bk is not None:
                    bases.append(bk)
            self.class_bases[key] = bases
            for meth in cdef.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                ann: Dict[str, tuple] = {}
                for arg in list(meth.args.args) + list(meth.args.kwonlyargs):
                    t = self._annotation_type(mi, arg.annotation)
                    if t is not None:
                        ann[arg.arg] = t
                for node in ast.walk(meth):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            continue
                        ftype = self._value_type(mi, node.value, ann)
                        if ftype is not None and t.attr not in fields:
                            fields[t.attr] = ftype

    def _annotation_type(self, mi: _ModInfo, ann) -> Optional[tuple]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Subscript):  # Optional[X] / "X | None"
            base = dotted_name(ann.value)
            if base.endswith("Optional"):
                return self._annotation_type(mi, ann.slice)
            return None
        if isinstance(ann, ast.BinOp):  # X | None
            return self._annotation_type(mi, ann.left)
        name = dotted_name(ann)
        if not name:
            return None
        return self._name_type(mi, name)

    def _name_type(self, mi: _ModInfo, name: str) -> Optional[tuple]:
        last = name.split(".")[-1]
        ck = self._resolve_class(mi, name)
        if ck is not None:
            return ("class", ck)
        if last.endswith(_EVENT_CTOR_SUFFIXES):
            return ("event",)
        if last.endswith(_QUEUE_CTOR_SUFFIXES):
            return ("queue",)
        return None

    def _value_type(self, mi: _ModInfo, value, ann: Dict[str, tuple]) -> Optional[tuple]:
        if isinstance(value, ast.BoolOp):
            for operand in value.values:
                t = self._value_type(mi, operand, ann)
                if t is not None:
                    return t
            return None
        if isinstance(value, ast.Call):
            name = dotted_name(value.func)
            if name and name not in _LOCK_CTORS:
                t = self._name_type(mi, name)
                if t is not None:
                    return t
                fk = self._resolve_function(mi, name)
                if fk is not None:
                    return self._return_type(fk)
            return None
        if isinstance(value, ast.Name):
            return ann.get(value.id)
        return None

    def _return_type(self, fnkey: Tuple[str, str]) -> Optional[tuple]:
        mi = self.mods.get(fnkey[0])
        fndef = mi.functions.get(fnkey[1]) if mi is not None else None
        if mi is None or fndef is None or fndef.returns is None:
            return None
        return self._annotation_type(mi, fndef.returns)

    # -- class hierarchy lookups -----------------------------------------

    def _iter_mro(self, key: Tuple[str, str], depth: int = 0):
        yield key
        if depth > 4:
            return
        for base in self.class_bases.get(key, ()):
            yield from self._iter_mro(base, depth + 1)

    def _class_lock_attr(self, key: Tuple[str, str], attr: str) -> Optional[str]:
        for k in self._iter_mro(key):
            hit = self.class_locks.get(k, {}).get(attr)
            if hit is not None:
                return hit
        return None

    def _class_field(self, key: Tuple[str, str], attr: str) -> Optional[tuple]:
        for k in self._iter_mro(key):
            hit = self.class_fields.get(k, {}).get(attr)
            if hit is not None:
                return hit
        return None

    def _call_targets(self, callee: Tuple[str, str]) -> Tuple[Tuple[str, str], ...]:
        """Sound may-analysis over dynamic dispatch: a call resolved to
        ``Class.meth`` may execute any subclass override (the static
        type is only an upper bound — e.g. a ``PackBackend``-typed
        receiver dispatching to the fleet's coalescing facade). Returns
        the resolved callee plus every transitive-subclass override
        that has a summary."""
        cached = self._targets_cache.get(callee)
        if cached is not None:
            return cached
        out: List[Tuple[str, str]] = [callee] if callee in self.summaries else []
        rel, qual = callee
        if "." in qual:
            cls, meth = qual.rsplit(".", 1)
            if self._children is None:
                children: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
                for sub, bases in self.class_bases.items():
                    for b in bases:
                        children.setdefault(b, []).append(sub)
                self._children = children
            seen = {(rel, cls)}
            work = list(self._children.get((rel, cls), ()))
            while work:
                sub = work.pop()
                if sub in seen:
                    continue
                seen.add(sub)
                override = (sub[0], f"{sub[1]}.{meth}")
                if override in self.summaries:
                    out.append(override)
                work.extend(self._children.get(sub, ()))
        result = tuple(sorted(out))
        self._targets_cache[callee] = result
        return result

    def _resolve_method(
        self, key: Tuple[str, str], name: str
    ) -> Optional[Tuple[str, str]]:
        for k in self._iter_mro(key):
            mi = self.mods.get(k[0])
            cdef = mi.classes.get(k[1]) if mi is not None else None
            if cdef is None:
                continue
            for meth in cdef.body:
                if (
                    isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and meth.name == name
                ):
                    return (k[0], f"{k[1]}.{name}")
        return None

    # -- per-function scanning -------------------------------------------

    def _scan_module(self, mi: _ModInfo) -> None:
        for fname, fndef in mi.functions.items():
            self._scan_function(mi, None, fname, fndef)
        for cname, cdef in mi.classes.items():
            for meth in cdef.body:
                if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scan_function(mi, cname, f"{cname}.{meth.name}", meth)

    def _scan_function(
        self, mi: _ModInfo, cls: Optional[str], qual: str, fndef
    ) -> None:
        fnkey = (mi.relpath, qual)
        if fnkey in self.summaries:
            return
        self.fn_defs[fnkey] = fndef
        summary = _FnSummary(fnkey)
        self.summaries[fnkey] = summary
        env: Dict[str, tuple] = {}
        for arg in list(fndef.args.args) + list(fndef.args.kwonlyargs):
            t = self._annotation_type(mi, arg.annotation)
            if t is not None:
                env[arg.arg] = t

        class_key = (mi.relpath, cls) if cls else None

        def lock_of(expr) -> Optional[str]:
            if isinstance(expr, ast.Name):
                hit = env.get(expr.id)
                if hit is not None and hit[0] == "lockid":
                    return hit[1]
                if expr.id in mi.module_locks:
                    return mi.module_locks[expr.id]
                imp = mi.from_names.get(expr.id)
                if imp is not None:
                    tm = self._ensure_module(imp[0])
                    if tm is not None and imp[1] in tm.module_locks:
                        return tm.module_locks[imp[1]]
                return None
            if isinstance(expr, ast.Attribute):
                base = expr.value
                if isinstance(base, ast.Name):
                    if base.id == "self" and class_key is not None:
                        return self._class_lock_attr(class_key, expr.attr)
                    bt = env.get(base.id)
                    if bt is not None and bt[0] == "class":
                        return self._class_lock_attr(bt[1], expr.attr)
                    target = mi.imports.get(base.id)
                    if target is not None:
                        tm = self._ensure_module(target)
                        if tm is not None:
                            return tm.module_locks.get(expr.attr)
                    return None
                bt = type_of(base)
                if bt is not None and bt[0] == "class":
                    return self._class_lock_attr(bt[1], expr.attr)
            return None

        def type_of(expr) -> Optional[tuple]:
            if isinstance(expr, ast.Name):
                hit = env.get(expr.id)
                if hit is not None and hit[0] != "lockid":
                    return hit
                return None
            if isinstance(expr, ast.Attribute):
                if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                    if class_key is not None:
                        return self._class_field(class_key, expr.attr)
                    return None
                bt = type_of(expr.value)
                if bt is not None and bt[0] == "class":
                    return self._class_field(bt[1], expr.attr)
                return None
            if isinstance(expr, ast.Call):
                name = dotted_name(expr.func)
                if name:
                    t = self._name_type(mi, name)
                    if t is not None:
                        return t
                    fk = self._resolve_function(mi, name)
                    if fk is not None:
                        return self._return_type(fk)
                if isinstance(expr.func, ast.Attribute):
                    # module-qualified call: `incremental.warm_state_for(...)`
                    base = expr.func.value
                    if isinstance(base, ast.Name) and base.id in mi.imports:
                        tm = self._ensure_module(mi.imports[base.id])
                        if tm is not None and expr.func.attr in tm.functions:
                            return self._return_type((tm.relpath, expr.func.attr))
                return None
            return None

        def resolve_callee(func) -> Optional[Tuple[str, str]]:
            if isinstance(func, ast.Name):
                return self._resolve_function(mi, func.id)
            if isinstance(func, ast.Attribute):
                base = func.value
                if isinstance(base, ast.Name):
                    if base.id == "self" and class_key is not None:
                        return self._resolve_method(class_key, func.attr)
                    if base.id in mi.imports:
                        tm = self._ensure_module(mi.imports[base.id])
                        if tm is not None and func.attr in tm.functions:
                            return (tm.relpath, func.attr)
                bt = type_of(base)
                if bt is not None and bt[0] == "class":
                    return self._resolve_method(bt[1], func.attr)
            return None

        def handle_call(node: ast.Call, held: Tuple[str, ...]) -> None:
            func = node.func
            name = dotted_name(func)
            line = node.lineno
            no_args = not node.args and not node.keywords
            if isinstance(func, ast.Attribute):
                attr = func.attr
                if attr == "acquire":
                    lid = lock_of(func.value)
                    if lid is not None:
                        summary.acquires.append(_Acquire(lid, line, held, fnkey))
                    return
                if attr == "release":
                    return
                if attr == "join":
                    # zero-arg join is a thread/process join (str.join and
                    # os.path.join always take arguments); a timeout kw or
                    # numeric-constant arg marks a bounded thread join —
                    # anything else (str.join(iterable)) is not blocking
                    if not node.args and not node.keywords:
                        summary.blocks.append(
                            _Block("join", line,
                                   f"{dotted_name(func.value) or 'thread'}.join()",
                                   held, fnkey, untimed=True)
                        )
                        return
                    timed = any(kw.arg == "timeout" for kw in node.keywords) or (
                        len(node.args) == 1
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, (int, float))
                    )
                    if timed:
                        summary.blocks.append(
                            _Block("join", line,
                                   f"{dotted_name(func.value) or 'thread'}.join(timeout)",
                                   held, fnkey)
                        )
                    return
                if attr == "wait":
                    lid = lock_of(func.value)
                    if lid is not None:
                        summary.blocks.append(
                            _Block("wait", line,
                                   f"Condition.wait on {lid.split('::')[-1]}",
                                   held, fnkey, own_lock=lid)
                        )
                        return
                    rt = type_of(func.value)
                    if rt is not None and rt[0] == "event":
                        timed = bool(node.args) or any(
                            kw.arg == "timeout" for kw in node.keywords
                        )
                        summary.blocks.append(
                            _Block("wait", line,
                                   f"{dotted_name(func.value) or 'event'}.wait()",
                                   held, fnkey, untimed=not timed)
                        )
                        return
                if attr in _QUEUE_BLOCKERS:
                    rt = type_of(func.value)
                    if rt is not None and rt[0] == "queue":
                        summary.blocks.append(
                            _Block("queue", line,
                                   f"{dotted_name(func.value) or 'queue'}.{attr}",
                                   held, fnkey)
                        )
                        return
                if attr in _DEVICE_SEAM:
                    summary.blocks.append(
                        _Block("device", line, f"{attr} (device dispatch)", held, fnkey)
                    )
                    # no return: the seam call still resolves below so
                    # lock acquisitions inside the dispatched callee (a
                    # coalescing facade's pack_jobs takes the dispatcher
                    # condition) propagate into the order graph
            if name == "time.sleep":
                summary.blocks.append(_Block("sleep", line, "time.sleep", held, fnkey))
                return
            if name == "open":
                summary.blocks.append(_Block("io", line, "open()", held, fnkey))
                return
            if name.startswith("subprocess."):
                summary.blocks.append(_Block("subprocess", line, name, held, fnkey))
                return
            if name in ("pickle.dump", "pickle.load"):
                summary.blocks.append(_Block("io", line, name, held, fnkey))
                return
            if name in _DEVICE_SEAM:
                summary.blocks.append(
                    _Block("device", line, f"{name} (device dispatch)", held, fnkey)
                )
                # fall through to call resolution: the seam call still
                # propagates lock acquisitions (a coalescing facade's
                # pack_jobs takes the dispatcher condition), only its
                # blocking kind is pinned to "device" above
            callee = resolve_callee(func)
            if callee is not None and callee != fnkey:
                desc = name
                if not desc and isinstance(func, ast.Attribute):
                    desc = func.attr
                summary.calls.append(_CallSite(callee, line, desc, held, fnkey))

        def visit(node, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested defs (thread bodies) scan with an empty held set
                # and do NOT feed the parent's may_acquire/may_block
                self._scan_function(mi, cls, f"{qual}.{node.name}", node)
                return
            if isinstance(node, (ast.ClassDef, ast.Lambda)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                cur = held
                for item in node.items:
                    lid = lock_of(item.context_expr)
                    if lid is not None:
                        summary.acquires.append(
                            _Acquire(lid, item.context_expr.lineno, cur, fnkey)
                        )
                        if lid not in cur:
                            cur = cur + (lid,)
                    else:
                        visit(item.context_expr, cur)
                for stmt in node.body:
                    visit(stmt, cur)
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lid = lock_of(node.value)
                        if lid is not None:
                            env[t.id] = ("lockid", lid)
                        else:
                            vt = self._value_type(mi, node.value, {
                                k: v for k, v in env.items() if v[0] != "lockid"
                            }) or type_of(node.value)
                            if vt is not None:
                                env[t.id] = vt
            if isinstance(node, ast.Call):
                handle_call(node, held)
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fndef.body:
            visit(stmt, ())

    # -- fixpoints and the order graph -----------------------------------

    def _fixpoints(self) -> None:
        for fnkey, s in self.summaries.items():
            self.may_acquire[fnkey] = {a.lock_id for a in s.acquires}
            self.may_block[fnkey] = {b.kind for b in s.blocks}
        changed = True
        guard = 0
        while changed and guard < 50:
            changed = False
            guard += 1
            for fnkey, s in self.summaries.items():
                for call in s.calls:
                    for callee in self._call_targets(call.callee):
                        acq = self.may_acquire[callee] - self.may_acquire[fnkey]
                        if acq:
                            self.may_acquire[fnkey] |= acq
                            changed = True
                        blk = self.may_block[callee] - self.may_block[fnkey]
                        if blk:
                            self.may_block[fnkey] |= blk
                            changed = True

    def _add_edge(self, src: str, dst: str, site: Tuple[str, int, str]) -> None:
        if src == dst:
            return  # RLock re-entry / same-lock nesting
        self.edges.setdefault((src, dst), []).append(site)

    def _build_edges(self) -> None:
        for fnkey, s in self.summaries.items():
            for a in s.acquires:
                site = (fnkey[0], a.line, fnkey[1])
                for h in a.held:
                    self._add_edge(h, a.lock_id, site)
            for call in s.calls:
                if not call.held:
                    continue
                site = (fnkey[0], call.line, fnkey[1])
                for callee in self._call_targets(call.callee):
                    for h in call.held:
                        for acq in self.may_acquire[callee]:
                            self._add_edge(h, acq, site)
        for key in self.edges:
            self.edges[key] = sorted(set(self.edges[key]))

    # -- rule outputs -----------------------------------------------------

    def lock_order_findings(self) -> List[Finding]:
        out: List[Finding] = []
        graph_edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        for (src, dst), sites in sorted(self.edges.items()):
            src_def = self.locks.get(src)
            dst_def = self.locks.get(dst)
            if src_def is None or dst_def is None:
                continue
            if src_def.sink and not dst_def.sink:
                path, line, sym = sites[0]
                out.append(Finding(
                    "lock-order", path, line, sym,
                    f"sink lock {src} (verified observability leaf) acquires "
                    f"coordination lock {dst} — sinks must stay leaves",
                ))
                continue
            if dst_def.sink:
                continue  # edge into a verified leaf cannot close a cycle
            graph_edges[(src, dst)] = sites
        # Tarjan SCC over the coordination-lock graph
        adj: Dict[str, List[str]] = {}
        for (src, dst) in graph_edges:
            adj.setdefault(src, []).append(dst)
            adj.setdefault(dst, [])
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(adj.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(adj.get(w, ())))))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    sccs.append(comp)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        for comp in sccs:
            if len(comp) < 2:
                continue
            members = sorted(comp)
            cyc_sites = sorted(
                site
                for (src, dst), sites in graph_edges.items()
                if src in comp and dst in comp
                for site in sites
            )
            path, line, sym = cyc_sites[0]
            if len(members) == 2:
                msg = (
                    f"inconsistent lock order: {members[0]} and {members[1]} "
                    f"are acquired in both orders (potential deadlock)"
                )
            else:
                msg = (
                    "potential deadlock: lock-order cycle among "
                    + ", ".join(members)
                )
            out.append(Finding("lock-order", path, line, sym, msg))
        return out

    def wait_findings(self) -> List[Finding]:
        out: List[Finding] = []

        def tracked(held: Tuple[str, ...], exclude: str = "") -> List[str]:
            keep = []
            for h in held:
                if h == exclude:
                    continue
                d = self.locks.get(h)
                if d is not None and not d.sink:
                    keep.append(h)
            return keep

        def marked(path: str, line: int) -> bool:
            owner = self.pctx.get(path)
            if owner is None:
                return False
            return scoped_marker_args(owner.lines, line, WAIT_RULE) is not None

        for fnkey, s in self.summaries.items():
            for b in s.blocks:
                path = fnkey[0]
                if b.untimed and not marked(path, b.line):
                    out.append(Finding(
                        WAIT_RULE, path, b.line, fnkey[1],
                        f"untimed {b.desc} — bound the wait and count the "
                        f"timeout outcome, never hang silently",
                    ))
                held = tracked(b.held, exclude=b.own_lock)
                if b.kind == "wait" and b.own_lock:
                    # Condition.wait on its own lock while holding ANOTHER
                    # tracked lock: the wait releases only its own lock
                    if held and not marked(path, b.line):
                        out.append(Finding(
                            WAIT_RULE, path, b.line, fnkey[1],
                            f"{b.desc} while also holding {', '.join(held)} — "
                            f"the wait releases only its own lock",
                        ))
                    continue
                if held and not marked(path, b.line):
                    out.append(Finding(
                        WAIT_RULE, path, b.line, fnkey[1],
                        f"blocking {b.kind} ({b.desc}) while holding "
                        f"{', '.join(held)}",
                    ))
            for call in s.calls:
                held = tracked(call.held)
                targets = self._call_targets(call.callee)
                if not held or not targets:
                    continue
                kinds = sorted(
                    {
                        k
                        for callee in targets
                        for k in self.may_block[callee]
                        if k in _PROPAGATED_KINDS
                    }
                )
                if kinds and not marked(call.fnkey[0], call.line):
                    out.append(Finding(
                        WAIT_RULE, call.fnkey[0], call.line, fnkey[1],
                        f"call to {call.callee[1]} may block "
                        f"({', '.join(kinds)}) while holding {', '.join(held)}",
                    ))
        dedup: Dict[Tuple[str, str, str, str], Finding] = {}
        for f in out:
            dedup.setdefault(f.baseline_key, f)
        return sorted(dedup.values(), key=lambda f: (f.path, f.line, f.message))


# ---------------------------------------------------------------------------
# process-boundary


def _sync_attrs(analyzer: _Analyzer, class_key: Tuple[str, str]) -> List[str]:
    attrs = sorted(analyzer.class_locks.get(class_key, {}))
    for attr, ftype in sorted(analyzer.class_fields.get(class_key, {}).items()):
        if ftype[0] in ("event", "queue"):
            attrs.append(attr)
    return attrs


def _process_boundary_findings(analyzer: _Analyzer) -> List[Finding]:
    out: List[Finding] = []
    # modules whose source can possibly reach a serializer root: the
    # payload walk is per-function and dominates this rule's cost, so
    # gate it on a constant-time source probe for the only two call
    # shapes _check_payload roots on (write_snapshot(...) / pickle.*)
    can_serialize: Dict[str, bool] = {}
    for fnkey, fndef in sorted(analyzer.fn_defs.items()):
        relpath, qual = fnkey
        cls = qual.rsplit(".", 2)[0] if "." in qual else ""
        simple = qual.rsplit(".", 1)[-1]
        if simple == "__getstate__" and cls and "." not in cls:
            out.extend(_check_getstate(analyzer, relpath, (relpath, cls), qual, fndef))
        if relpath not in can_serialize:
            mi = analyzer.mods.get(relpath)
            src = mi.ctx.source if mi is not None else ""
            can_serialize[relpath] = "write_snapshot" in src or "pickle." in src
        if (
            can_serialize[relpath]
            or "payload" in simple
            or simple == "__getstate__"
        ):
            out.extend(_check_payload(analyzer, relpath, qual, fndef))
    dedup: Dict[Tuple[str, str, str, str], Finding] = {}
    for f in out:
        dedup.setdefault(f.baseline_key, f)
    return sorted(dedup.values(), key=lambda f: (f.path, f.line, f.message))


def _check_getstate(
    analyzer: _Analyzer,
    relpath: str,
    class_key: Tuple[str, str],
    qual: str,
    fndef,
) -> List[Finding]:
    attrs = _sync_attrs(analyzer, class_key)
    if not attrs:
        return []
    out: List[Finding] = []
    for node in ast.walk(fndef):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        val = node.value
        whole_dict = False
        if isinstance(val, ast.Attribute) and val.attr == "__dict__":
            whole_dict = True
        if (
            isinstance(val, ast.Call)
            and dotted_name(val.func) == "dict"
            and val.args
            and isinstance(val.args[0], ast.Attribute)
            and val.args[0].attr == "__dict__"
        ):
            whole_dict = True
        if whole_dict:
            out.append(Finding(
                "process-boundary", relpath, node.lineno, qual,
                f"__getstate__ serializes self.__dict__ of a class holding "
                f"synchronization primitives ({', '.join(attrs)}) — strip "
                f"them before crossing the process boundary",
            ))
            continue
        leaked = sorted({
            sub.attr
            for sub in ast.walk(val)
            if isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
            and sub.attr in attrs
        })
        if leaked:
            out.append(Finding(
                "process-boundary", relpath, node.lineno, qual,
                f"__getstate__ payload embeds synchronization primitives "
                f"({', '.join(leaked)}) — they do not survive a process "
                f"boundary",
            ))
    return out


def _base_name(expr) -> Optional[str]:
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _check_payload(
    analyzer: _Analyzer, relpath: str, qual: str, fndef
) -> List[Finding]:
    simple = qual.rsplit(".", 1)[-1]
    roots: Set[str] = set()
    body_nodes = [n for n in ast.walk(fndef) if not isinstance(n, ast.arguments)]
    for node in body_nodes:
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            last = fname.split(".")[-1] if fname else (
                node.func.attr if isinstance(node.func, ast.Attribute) else ""
            )
            if last in _SERIALIZER_NAMES and (
                last == "write_snapshot" or fname.startswith("pickle.")
            ):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        roots.add(arg.id)
    if "payload" in simple or simple == "__getstate__":
        for node in body_nodes:
            if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
                roots.add(node.value.id)
    if not roots:
        return []

    # stores: (target name, value expr, line) — assigns, subscript
    # stores, and container-mutator calls, nested defs excluded
    stores: List[Tuple[str, ast.expr, int]] = []

    def collect(node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                name = _base_name(t)
                if name is not None:
                    stores.append((name, node.value, node.lineno))
        elif isinstance(node, ast.AugAssign):
            name = _base_name(node.target)
            if name is not None:
                stores.append((name, node.value, node.lineno))
        elif isinstance(node, ast.AnnAssign):
            # `payload: dict = {...}` — without this the reach analysis
            # stops at any annotated assignment and everything flowing
            # into the payload through it goes unchecked
            if node.value is not None:
                name = _base_name(node.target)
                if name is not None:
                    stores.append((name, node.value, node.lineno))
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _REACH_MUTATORS:
                name = _base_name(node.func.value)
                if name is not None:
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords if kw.value is not None
                    ]:
                        stores.append((name, arg, node.lineno))
        for child in ast.iter_child_nodes(node):
            collect(child)

    for stmt in fndef.body:
        collect(stmt)

    # reverse reach: names whose contents can flow into a root
    reach = set(roots)
    changed = True
    while changed:
        changed = False
        for target, value, _line in stores:
            if target not in reach:
                continue
            for sub in ast.walk(value):
                if isinstance(sub, ast.Name) and sub.id not in reach:
                    reach.add(sub.id)
                    changed = True

    # ordinal taint: names passed through a sig_for_id() translator
    translators: Set[str] = set()
    for target, value, _line in stores:
        if isinstance(value, ast.Call):
            fname = dotted_name(value.func)
            if fname.split(".")[-1] == "sig_for_id":
                translators.add(target)
    tainted: Set[str] = set()
    for node in body_nodes:
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if (
                node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in translators
            ):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        tainted.add(arg.id)
        elif isinstance(node, ast.Subscript):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in translators
                and isinstance(node.slice, ast.Name)
            ):
                tainted.add(node.slice.id)
    # names holding TRANSLATED content are clean even if later re-used
    clean: Set[str] = set()
    for target, value, _line in stores:
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Attribute):
            if (
                value.func.attr == "get"
                and isinstance(value.func.value, ast.Name)
                and value.func.value.id in translators
            ):
                clean.add(target)
    tainted -= clean

    def walk_skipping_translations(node):
        """Like ast.walk, but does not descend into translator lookups
        (``sig_names.get(sid)`` / ``sig_names[sid]``) — the sanctioned
        ordinal→content translation is exactly where ordinals appear."""
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if (
                node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in translators
            ):
                return
        if isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Name) and node.value.id in translators:
                return
        yield node
        for child in ast.iter_child_nodes(node):
            yield from walk_skipping_translations(child)

    out: List[Finding] = []
    for target, value, line in stores:
        if target not in reach:
            continue
        for sub in walk_skipping_translations(value):
            if isinstance(sub, ast.Call):
                fname = dotted_name(sub.func)
                if fname == "id":
                    out.append(Finding(
                        "process-boundary", relpath, line, qual,
                        "serialized payload embeds id() — process-local "
                        "identity does not survive a process boundary",
                    ))
                elif fname in _LOCK_CTORS or fname in (
                    "threading.Event", "threading.Semaphore",
                ):
                    out.append(Finding(
                        "process-boundary", relpath, line, qual,
                        f"serialized payload embeds a threading primitive "
                        f"({fname})",
                    ))
                elif fname == "open":
                    out.append(Finding(
                        "process-boundary", relpath, line, qual,
                        "serialized payload embeds an open handle",
                    ))
            elif isinstance(sub, ast.Name) and sub.id in tainted:
                out.append(Finding(
                    "process-boundary", relpath, line, qual,
                    f"serialized payload stores process-local interned "
                    f"ordinal '{sub.id}' — persist the signature content "
                    f"and re-intern on load",
                ))
    return out


# ---------------------------------------------------------------------------
# shared analyzer + rule registration


def _shared(pctx: ProjectContext) -> _Analyzer:
    cached = getattr(pctx, "_concurrency_analyzer", None)
    if cached is not None:
        return cached
    analyzer = _Analyzer(pctx)
    analyzer.run()
    pctx._concurrency_analyzer = analyzer  # type: ignore[attr-defined]
    return analyzer


@project_rule(
    "lock-order",
    "the global lock-order graph must be acyclic and sink locks must stay leaves",
)
def lock_order_rule(pctx: ProjectContext):
    return _shared(pctx).lock_order_findings()


@project_rule(
    WAIT_RULE,
    "no blocking operation (I/O, device dispatch, queue handoff, join, "
    "cross-lock wait) while holding a coordination lock; every join/Event "
    "wait is bounded",
)
def wait_under_lock_rule(pctx: ProjectContext):
    return _shared(pctx).wait_findings()


@project_rule(
    "process-boundary",
    "values crossing a serialization boundary must be content-addressed: "
    "no id(), threading primitives, open handles, or process ordinals",
)
def process_boundary_rule(pctx: ProjectContext):
    return _process_boundary_findings(_shared(pctx))


# ---------------------------------------------------------------------------
# runtime-witness surface (consumed by analysis/lockwitness.py)

_WITNESS_CACHE: Dict[str, _Analyzer] = {}


def _repo_analyzer(root: Optional[str] = None) -> _Analyzer:
    root = root or repo_root()
    hit = _WITNESS_CACHE.get(root)
    if hit is not None:
        return hit
    pctx = ProjectContext([], root, DEFAULT_CONFIG)
    analyzer = _shared(pctx)
    _WITNESS_CACHE[root] = analyzer
    return analyzer


def witness_inventory(root: Optional[str] = None) -> Dict[Tuple[str, int], Tuple[str, str]]:
    """(relpath, creation line) -> (lock_id, ctor kind) for every
    non-sink lock: what the runtime witness instruments."""
    analyzer = _repo_analyzer(root)
    return {
        (d.relpath, d.line): (d.lock_id, d.kind)
        for d in analyzer.locks.values()
        if not d.sink
    }


def static_order_graph(root: Optional[str] = None) -> Set[Tuple[str, str]]:
    """Every static lock-order edge (src held when dst acquired) —
    the superset the runtime witness checks observed edges against."""
    analyzer = _repo_analyzer(root)
    return set(analyzer.edges.keys())


def lock_inventory(root: Optional[str] = None) -> List[LockDef]:
    """The full discovered inventory (sinks included), sorted — for
    docs and the witness tests."""
    analyzer = _repo_analyzer(root)
    return sorted(analyzer.locks.values(), key=lambda d: d.lock_id)
