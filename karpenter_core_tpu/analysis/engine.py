"""Rule engine: walks files, parses once, runs registered rules, applies
suppressions and the baseline. stdlib ``ast`` only — the default run
never imports jax (shape-contract verification is a separate mode, see
``shape_contracts.py``).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .findings import Baseline, Finding, is_suppressed, split_by_baseline

# ---------------------------------------------------------------------------
# configuration


@dataclass(frozen=True)
class AnalysisConfig:
    """Repo-specific knowledge the rules key off. Paths are repo-relative
    posix suffixes/prefixes."""

    # modules on the solve hot path where any host<->device sync is a
    # latency bug unless explicitly annotated (ISSUE 3: an accidental
    # np.asarray in a hot loop re-introduces per-pod host serialization)
    device_hot_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/solver/pack.py",
        "karpenter_core_tpu/solver/sharding.py",
        "karpenter_core_tpu/solver/backend.py",
        "karpenter_core_tpu/solver/kernels.py",
        "karpenter_core_tpu/solver/pallas_kernels.py",
        "karpenter_core_tpu/solver/backends/lp.py",
    )
    # device-hot solver modules held to the deviceplane registration seam
    # (ISSUE 16): every jax.jit / shard_map entry point must register
    # through tracing.deviceplane (observe_jit decorator or wrap() around
    # the jit call) so recompiles are attributed to the triggering solve
    jit_registry_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/solver/pack.py",
        "karpenter_core_tpu/solver/sharding.py",
        "karpenter_core_tpu/solver/backend.py",
        "karpenter_core_tpu/solver/kernels.py",
        "karpenter_core_tpu/solver/pallas_kernels.py",
        "karpenter_core_tpu/solver/backends/lp.py",
        "karpenter_core_tpu/disruption/tpu_repack.py",
    )
    # control-plane packages that must never import jax: a stray jnp op
    # in a controller thread would initialize the backend (and possibly
    # block on a dead TPU plugin) outside the solver's probe/fallback
    host_only_prefixes: Tuple[str, ...] = (
        "karpenter_core_tpu/state/",
        "karpenter_core_tpu/metrics/",
        "karpenter_core_tpu/operator/",
        "karpenter_core_tpu/kube/",
        "karpenter_core_tpu/apis/",
        "karpenter_core_tpu/events/",
        "karpenter_core_tpu/scheduling/",
        "karpenter_core_tpu/scheduler/",
        "karpenter_core_tpu/provisioning/",
        "karpenter_core_tpu/lifecycle/",
        "karpenter_core_tpu/utils/",
        "karpenter_core_tpu/cloudprovider/",
        "karpenter_core_tpu/tracing/",
        "karpenter_core_tpu/serving/",
        "karpenter_core_tpu/fleet/",
    )
    # cross-module device-array-returning functions (jit-decorated
    # functions in the SAME module are detected automatically)
    device_producers: Tuple[str, ...] = (
        "sharded_batch_pack",
        "sharded_prefix_screen",
        "sharded_compat",
        "allowed_sharded",
        "device_put",
        "compat_pallas",
        "allowed_pallas",
        "ffd_pack",
        "ffd_pack_batched",
        "pack_existing",
        "compat_kernel",
        "offering_kernel",
        "allowed_kernel",
        "prefix_screen_kernel",
        "single_screen_kernel",
    )
    # modules holding cross-solve memoization (ISSUE 5): the cachesound
    # family verifies every memo key witnesses its read-set here
    cache_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/solver/incremental.py",
        "karpenter_core_tpu/solver/podcache.py",
        "karpenter_core_tpu/solver/solver.py",
        "karpenter_core_tpu/solver/encode.py",
        "karpenter_core_tpu/solver/merge.py",
        "karpenter_core_tpu/disruption/engine.py",
        # plan-quality pack backends (ISSUE 8): the LP relaxation memo
        "karpenter_core_tpu/solver/backends/__init__.py",
        "karpenter_core_tpu/solver/backends/lp.py",
        # fleet mega-solve (ISSUE 9): the tenant envelope/canonical
        # catalog memos and the fleet-wide job-skeleton content plane
        "karpenter_core_tpu/fleet/registry.py",
        "karpenter_core_tpu/fleet/megasolve.py",
        # pod-axis mega-shard (ISSUE 11): pod_shard_token contributes
        # job-memo key material (consumed by incremental.pack_engine_token)
        "karpenter_core_tpu/solver/sharding.py",
        # constraint tensorization (ISSUE 12): the port/volume mask
        # builders whose outputs ride job-memo keys (port_features) and
        # existing-pack masks
        "karpenter_core_tpu/solver/constraint_tensors.py",
    )
    # warm-state persistence modules (ISSUE 13): the snapshot/restore
    # seam whose restore paths the cache-persist rule holds to the
    # re-anchoring contract (live generations only, tenant scope
    # preserved, schema/contract verified before trusting a payload,
    # ISSUE 17 — the compile-cache plane restored only behind a
    # jax/jaxlib/platform fingerprint comparison, and ISSUE 19 — the
    # lprelax warm-dual plane restored only behind finite-price and
    # iteration-budget witnesses); prewarm.py replays the restored
    # jitsig rows and backends/lp.py owns the persisted lprelax plane —
    # both ride the same rule set
    warmstore_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/solver/warmstore.py",
        "karpenter_core_tpu/solver/prewarm.py",
        "karpenter_core_tpu/solver/backends/lp.py",
    )
    # informer-state modules whose mutators must bump Cluster.generation()
    state_modules: Tuple[str, ...] = ("karpenter_core_tpu/state/cluster.py",)
    # provider modules whose catalog mutators must bump catalog_generation()
    provider_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/cloudprovider/fake.py",
        "karpenter_core_tpu/cloudprovider/types.py",
    )
    # serving-pipeline modules: multi-threaded by design, held to the
    # pipeline-safety rule (lock-guarded or queue-handed-off sharing);
    # the fleet engine's worker threads are held to the same rule
    serving_prefixes: Tuple[str, ...] = (
        "karpenter_core_tpu/serving/",
        "karpenter_core_tpu/fleet/",
    )
    # modules whose cluster-API reads define the generation-relevant
    # field set (what the solver's caches can actually observe)
    cluster_consumer_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/solver/solver.py",
        "karpenter_core_tpu/solver/incremental.py",
        "karpenter_core_tpu/provisioning/provisioner.py",
        "karpenter_core_tpu/scheduler/scheduler.py",
        "karpenter_core_tpu/disruption/helpers.py",
    )
    # control-loop packages held to clock discipline (ISSUE 15): any
    # duration/timeout/expiry math must read time.monotonic(); wall
    # clock is reserved for stamps that cross a process boundary
    # (leases, deletionTimestamp, condition transitions) under a scoped
    # `# analysis: allow-clock(reason)` marker
    control_loop_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/disruption/",
        "karpenter_core_tpu/operator/",
        "karpenter_core_tpu/serving/",
        "karpenter_core_tpu/lifecycle/",
        "karpenter_core_tpu/provisioning/",
        "karpenter_core_tpu/kube/",
        "karpenter_core_tpu/state/",
    )
    # every module that creates or acquires a threading primitive (ISSUE
    # 18): the concurrency rule family discovers the lock inventory here,
    # builds the global lock-order graph across the set, and scopes the
    # wait-under-lock / process-boundary checks to it. Cross-file
    # resolution loads the full set even on --changed-only runs.
    concurrency_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/serving/pipeline.py",
        "karpenter_core_tpu/serving/queues.py",
        "karpenter_core_tpu/serving/latency.py",
        "karpenter_core_tpu/provisioning/batcher.py",
        "karpenter_core_tpu/provisioning/provisioner.py",
        "karpenter_core_tpu/fleet/megasolve.py",
        "karpenter_core_tpu/fleet/registry.py",
        "karpenter_core_tpu/fleet/scheduler.py",
        "karpenter_core_tpu/solver/solver.py",
        "karpenter_core_tpu/solver/incremental.py",
        "karpenter_core_tpu/solver/warmstore.py",
        "karpenter_core_tpu/solver/prewarm.py",
        "karpenter_core_tpu/solver/backends/__init__.py",
        "karpenter_core_tpu/solver/podcache.py",
        "karpenter_core_tpu/solver/oracle_bridge.py",
        "karpenter_core_tpu/state/cluster.py",
        "karpenter_core_tpu/kube/client.py",
        "karpenter_core_tpu/kube/restclient.py",
        "karpenter_core_tpu/kube/faults.py",
        "karpenter_core_tpu/cloudprovider/fake.py",
        "karpenter_core_tpu/operator/server.py",
        "karpenter_core_tpu/metrics/registry.py",
        "karpenter_core_tpu/events/recorder.py",
        "karpenter_core_tpu/utils/atomic.py",
        "karpenter_core_tpu/tracing/tracer.py",
        "karpenter_core_tpu/tracing/flightrec.py",
        "karpenter_core_tpu/tracing/deviceplane.py",
        "karpenter_core_tpu/native/__init__.py",
    )
    # modules a warmstore restore re-animates (ISSUE 20): an import-time
    # KARPENTER_TPU_* read here is frozen before restore() can run, so a
    # restored process can never re-decide it — the knob-inventory rule
    # forces such reads behind functions (or a scoped marker stating why
    # the freeze is deliberate, e.g. a static kernel shape)
    restorable_modules: Tuple[str, ...] = (
        "karpenter_core_tpu/solver/warmstore.py",
        "karpenter_core_tpu/solver/prewarm.py",
        "karpenter_core_tpu/solver/backends/lp.py",
        "karpenter_core_tpu/solver/backends/__init__.py",
        "karpenter_core_tpu/solver/solver.py",
        "karpenter_core_tpu/solver/incremental.py",
        "karpenter_core_tpu/solver/pack.py",
        "karpenter_core_tpu/solver/sharding.py",
        "karpenter_core_tpu/solver/backend.py",
        "karpenter_core_tpu/fleet/registry.py",
        "karpenter_core_tpu/fleet/megasolve.py",
    )
    # modules whose outputs must be iteration-order deterministic
    # (ISSUE 20): plan emission, fingerprints/stable hashes, and
    # warmstore payloads all cross a process boundary, so unordered
    # producers (unsorted listdir/glob, bare popitem, set iteration)
    # are findings here — scoped `# analysis: allow-determinism(why)`
    determinism_prefixes: Tuple[str, ...] = (
        "karpenter_core_tpu/solver/",
        "karpenter_core_tpu/fleet/",
        "karpenter_core_tpu/native/",
        "karpenter_core_tpu/tracing/capture.py",
    )


DEFAULT_CONFIG = AnalysisConfig()


# ---------------------------------------------------------------------------
# shared parse cache: one AST per (path, mtime, size) across every rule
# family AND every analyze_paths call in the process. The tier-1 meta-
# tests and the cachesound mutation harness re-analyze near-identical
# file sets dozens of times; without this each run would re-parse the
# whole package (solver.py alone is ~4.3k lines).

_PARSE_CACHE: Dict[str, Tuple[Tuple[int, int], str, ast.Module]] = {}
_PARSE_CACHE_MAX = 1024


def parse_file(path: str) -> Tuple[str, ast.Module]:
    """Source + AST for ``path``, cached on (mtime_ns, size). Raises
    OSError/SyntaxError/UnicodeDecodeError like open/ast.parse."""
    ap = os.path.abspath(path)
    st = os.stat(ap)
    sig = (st.st_mtime_ns, st.st_size)
    hit = _PARSE_CACHE.get(ap)
    if hit is not None and hit[0] == sig:
        return hit[1], hit[2]
    with open(ap, "r", encoding="utf-8") as f:
        source = f.read()
    tree = ast.parse(source, filename=ap)
    if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
        _PARSE_CACHE.clear()  # content-addressed: only costs re-parsing
    _PARSE_CACHE[ap] = (sig, source, tree)
    return source, tree


@dataclass
class FileContext:
    relpath: str  # repo-relative posix path
    source: str
    lines: List[str]
    tree: ast.Module
    config: AnalysisConfig

    def walk(self) -> List[ast.AST]:
        """Memoized full-tree preorder walk. Every rule that scans the
        whole module should iterate this instead of re-walking the tree
        — with ~16 rule families the redundant traversals dominate the
        CLI's wall time."""
        nodes = getattr(self, "_walk_cache", None)
        if nodes is None:
            nodes = list(ast.walk(self.tree))
            object.__setattr__(self, "_walk_cache", nodes)
        return nodes

    def is_device_hot(self) -> bool:
        return any(self.relpath.endswith(m) for m in self.config.device_hot_modules)

    def is_host_only(self) -> bool:
        return any(self.relpath.startswith(p) for p in self.config.host_only_prefixes)


# ---------------------------------------------------------------------------
# registry

RuleFn = Callable[[FileContext], Iterable[Finding]]

_RULES: Dict[str, Tuple[RuleFn, str]] = {}


def rule(name: str, description: str):
    def deco(fn: RuleFn) -> RuleFn:
        _RULES[name] = (fn, description)
        return fn

    return deco


@dataclass
class ProjectContext:
    """What a project-level rule sees: every file of the run, plus
    on-demand access (through the shared parse cache) to repo modules the
    rule needs for cross-file reasoning even when the run was scoped to a
    subset (``--changed-only``)."""

    files: List[FileContext]
    root: str
    config: AnalysisConfig

    def __post_init__(self) -> None:
        self._by_rel: Dict[str, FileContext] = {f.relpath: f for f in self.files}

    def get(self, relpath: str) -> Optional[FileContext]:
        """The FileContext for a repo-relative path — from this run's
        set, or loaded (and cached) from disk under ``root``."""
        ctx = self._by_rel.get(relpath)
        if ctx is not None:
            return ctx
        path = os.path.join(self.root, relpath.replace("/", os.sep))
        try:
            source, tree = parse_file(path)
        except (OSError, SyntaxError, UnicodeDecodeError):
            return None
        ctx = FileContext(relpath, source, source.splitlines(), tree, self.config)
        self._by_rel[relpath] = ctx
        return ctx

    def matching(self, suffixes: Sequence[str]) -> List[FileContext]:
        """Files participating in a module-scoped project rule: every
        loaded file whose relpath ends with a configured suffix, plus
        the configured modules themselves loaded from the repo root —
        and, for fixture runs rooted outside the package, every file
        (snippets opt in by not living under karpenter_core_tpu/)."""
        out: List[FileContext] = []
        seen = set()
        for f in self.files:
            hit = any(f.relpath.endswith(s) for s in suffixes) or not f.relpath.startswith(
                "karpenter_core_tpu/"
            )
            if hit and f.relpath not in seen:
                seen.add(f.relpath)
                out.append(f)
        for s in suffixes:
            if s in seen:
                continue
            ctx = self.get(s)
            if ctx is not None and ctx.relpath not in seen:
                seen.add(ctx.relpath)
                out.append(ctx)
        return out


ProjectRuleFn = Callable[[ProjectContext], Iterable[Finding]]

_PROJECT_RULES: Dict[str, Tuple[ProjectRuleFn, str]] = {}


def project_rule(name: str, description: str):
    """A rule that reasons across files (call graphs, key/read-set
    comparisons). Runs once per analysis over the whole file set."""

    def deco(fn: ProjectRuleFn) -> ProjectRuleFn:
        _PROJECT_RULES[name] = (fn, description)
        return fn

    return deco


def registered_rules() -> Dict[str, str]:
    _load_rules()
    out = {name: desc for name, (_, desc) in _RULES.items()}
    out.update({name: desc for name, (_, desc) in _PROJECT_RULES.items()})
    return dict(sorted(out.items()))


_LOADED = False


def _load_rules() -> None:
    global _LOADED
    if not _LOADED:
        from . import (  # noqa: F401
            cachesound,
            clock,
            concurrency,
            configprov,
            determinism,
            hygiene,
            hostsync,
            jitregistry,
            locks,
            pipelinesafety,
            tracersafety,
        )

        _LOADED = True


# ---------------------------------------------------------------------------
# shared AST helpers (used by several rules)


def qualify(tree: ast.Module) -> Dict[ast.AST, str]:
    """node → enclosing 'Class.method' / 'function' symbol map."""
    out: Dict[ast.AST, str] = {}

    def walk(node: ast.AST, sym: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_sym = sym
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                child_sym = f"{sym}.{child.name}" if sym else child.name
            out[child] = child_sym
            walk(child, child_sym)

    walk(tree, "")
    return out


def symbol_at(tree: ast.Module, node: ast.AST, cache: dict) -> str:
    if "qual" not in cache:
        cache["qual"] = qualify(tree)
    return cache["qual"].get(node, "")


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def jit_decoration(fn: ast.AST) -> Optional[dict]:
    """If ``fn`` is decorated with jax.jit / jax.vmap (bare or via
    functools.partial), return {'kind', 'static_names', 'static_nums'};
    else None."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        inner = None
        if name.endswith("partial") and isinstance(dec, ast.Call) and dec.args:
            inner = dec.args[0]
            iname = dotted_name(inner)
            if iname in ("jax.jit", "jit", "jax.vmap", "vmap"):
                info = {"kind": iname.split(".")[-1], "static_names": [], "static_nums": []}
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        info["static_names"] = _const_strings(kw.value)
                    elif kw.arg == "static_argnums":
                        info["static_nums"] = _const_ints(kw.value)
                return info
        elif name in ("jax.jit", "jit", "jax.vmap", "vmap"):
            info = {"kind": name.split(".")[-1], "static_names": [], "static_nums": []}
            if isinstance(dec, ast.Call):
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        info["static_names"] = _const_strings(kw.value)
                    elif kw.arg == "static_argnums":
                        info["static_nums"] = _const_ints(kw.value)
            return info
    return None


def _const_strings(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


# ---------------------------------------------------------------------------
# runner


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)  # active (gate fails)
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[dict] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline and not self.parse_errors

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": self.stale_baseline,
            "parse_errors": self.parse_errors,
        }


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def analyze_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    config: Optional[AnalysisConfig] = None,
    baseline: Optional[Baseline] = None,
    rules: Optional[Sequence[str]] = None,
) -> Report:
    """Run the rule set over ``paths`` (files or directories)."""
    _load_rules()
    config = config or DEFAULT_CONFIG
    if root is None:
        root = os.getcwd()
    selected = {
        name: fn for name, (fn, _) in _RULES.items() if rules is None or name in rules
    }
    selected_project = {
        name: fn
        for name, (fn, _) in _PROJECT_RULES.items()
        if rules is None or name in rules
    }
    report = Report()
    raw: List[Finding] = []
    contexts: List[FileContext] = []
    for path in iter_python_files(paths):
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
        rel = rel.replace(os.sep, "/")
        try:
            source, tree = parse_file(path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            report.parse_errors.append(f"{rel}: {e}")
            continue
        ctx = FileContext(rel, source, source.splitlines(), tree, config)
        contexts.append(ctx)
        report.files_scanned += 1
        for fn in selected.values():
            for finding in fn(ctx):
                if is_suppressed(finding, ctx.lines):
                    report.suppressed.append(finding)
                else:
                    raw.append(finding)
    if selected_project:
        pctx = ProjectContext(contexts, os.path.abspath(root), config)
        for fn in selected_project.values():
            for finding in fn(pctx):
                owner = pctx.get(finding.path)
                if owner is not None and is_suppressed(finding, owner.lines):
                    report.suppressed.append(finding)
                else:
                    raw.append(finding)
    active, baselined, stale = split_by_baseline(raw, baseline)
    report.findings = sorted(active, key=lambda f: (f.path, f.line, f.rule))
    report.baselined = baselined
    report.stale_baseline = stale
    return report


def repo_root() -> str:
    """The repo checkout containing this package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def analyze_repo(
    baseline_path: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    use_baseline: bool = True,
) -> Report:
    """The gate entrypoint: scan the package with the checked-in
    baseline."""
    root = repo_root()
    pkg = os.path.join(root, "karpenter_core_tpu")
    baseline = None
    if use_baseline:
        baseline = Baseline.load(baseline_path or default_baseline_path())
    return analyze_paths([pkg], root=root, baseline=baseline, rules=rules)
