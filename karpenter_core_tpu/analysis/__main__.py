"""CLI: ``python -m karpenter_core_tpu.analysis``.

Exit 0 when the repo is clean (every finding fixed, suppressed with a
marker, or baselined — and no stale baseline entries); 1 otherwise.
``--format json`` emits machine-readable findings for CI tooling, like
``profile_solve.py`` does for perf.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .engine import (
    analyze_paths,
    default_baseline_path,
    registered_rules,
    repo_root,
)
from .findings import Baseline


def _changed_python_files(root):
    """Absolute paths of .py files changed vs the merge base with the
    main branch (committed + staged + working tree + untracked), or None
    when git is unavailable. The merge base degrades to HEAD on main
    itself, which scopes the run to uncommitted work — the pre-push
    shape ``hack/analyze.sh`` wants."""
    import subprocess

    def git(*argv):
        try:
            p = subprocess.run(
                ["git", *argv], cwd=root, capture_output=True, text=True, timeout=15
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        return p.stdout if p.returncode == 0 else None

    if git("rev-parse", "--git-dir") is None:
        return None
    base = None
    for ref in ("origin/main", "main", "origin/master", "master"):
        out = git("merge-base", "HEAD", ref)
        if out:
            base = out.strip()
            break
    diff = git("diff", "--name-only", base or "HEAD")
    untracked = git("ls-files", "--others", "--exclude-standard")
    names = set()
    for blob in (diff, untracked):
        if blob:
            names.update(line.strip() for line in blob.splitlines() if line.strip())
    return sorted(
        os.path.join(root, n.replace("/", os.sep))
        for n in names
        if n.endswith(".py") and os.path.exists(os.path.join(root, n))
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_core_tpu.analysis",
        description="Repo-native static analysis: lock discipline, host-sync "
        "boundaries, tracer safety, hygiene, shape contracts.",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to scan (default: the karpenter_core_tpu package)",
    )
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: the checked-in analysis/baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true", help="report grandfathered findings too"
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from the current findings and exit",
    )
    parser.add_argument(
        "--rules", default=None, help="comma-separated rule subset (see --list-rules)"
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="scope the scan to .py files changed vs the git merge base "
        "(falls back to uncommitted changes; project rules like cachesound "
        "still load their configured cross-file module set)",
    )
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument(
        "--knobs",
        action="store_true",
        help="print the KARPENTER_TPU_* knob registry (the README "
        "Configuration table; --format json for per-site detail) and exit",
    )
    parser.add_argument(
        "--contracts",
        action="store_true",
        help="also verify @contract shape declarations via jax.eval_shape",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, desc in registered_rules().items():
            print(f"{name}: {desc}")
        return 0

    if args.knobs:
        from .configprov import knob_rows, knob_table_lines, repo_registry

        registry = repo_registry()
        if args.format == "json":
            json.dump(knob_rows(registry), sys.stdout, indent=2)
            print()
        else:
            for line in knob_table_lines(registry):
                print(line)
        return 0

    root = repo_root()
    paths = args.paths or [os.path.join(root, "karpenter_core_tpu")]
    if args.changed_only:
        changed = _changed_python_files(root)
        if changed is None:
            print("--changed-only: not a git checkout, scanning everything")
        else:
            paths = [p for p in changed if p.startswith(os.path.join(root, "karpenter_core_tpu"))]
            if not paths:
                print("--changed-only: no changed python files; clean")
                return 0
    rules = args.rules.split(",") if args.rules else None
    baseline_path = args.baseline or default_baseline_path()
    baseline = None if args.no_baseline else Baseline.load(baseline_path)

    report = analyze_paths(paths, root=root, baseline=baseline, rules=rules)
    if args.changed_only:
        # a scoped scan cannot see the files grandfathered findings live
        # in — only the full run may police baseline staleness
        report.stale_baseline = []

    if args.write_baseline:
        merged = report.findings + report.baselined
        Baseline.from_findings(merged).save(baseline_path)
        print(f"baseline: {len(merged)} findings -> {baseline_path}")
        return 0

    contract_results = []
    contracts_ok = True
    if args.contracts:
        # pin the platform before jax loads: a dead TPU plugin must cost
        # nothing here (same rationale as solver/backend.py)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from .shape_contracts import verify_contracts

        contract_results = verify_contracts()
        contracts_ok = all(r.ok for r in contract_results)

    if args.format == "json":
        payload = report.to_dict()
        if args.contracts:
            payload["contracts"] = [
                {"name": r.name, "ok": r.ok, "checked": r.checked, "detail": r.detail}
                for r in contract_results
            ]
            payload["ok"] = payload["ok"] and contracts_ok
        json.dump(payload, sys.stdout, indent=2)
        print()
    else:
        for f in report.findings:
            print(f.format())
        for e in report.stale_baseline:
            print(
                f"STALE baseline entry (fixed? run --write-baseline): "
                f"{e['path']}: {e['rule']}: {e['message']}"
            )
        for e in report.parse_errors:
            print(f"PARSE ERROR: {e}")
        for r in contract_results:
            status = "ok" if r.ok else "FAIL"
            mode = "eval_shape" if r.checked else "runtime-only"
            print(f"contract {r.name}: {status} [{mode}] {r.detail}")
        print(
            f"{report.files_scanned} files; {len(report.findings)} findings, "
            f"{len(report.suppressed)} suppressed, {len(report.baselined)} baselined"
            + (f", {len(contract_results)} contracts" if args.contracts else "")
        )
    return 0 if (report.ok and contracts_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
