"""clock-discipline: wall-clock time in control-loop duration logic.

The chaos pack (ISSUE 15) injects clock skew; any duration, timeout, or
expiry computed from ``time.time()`` / ``datetime.now()`` in a control
loop silently breaks under skew or NTP step (a lease that "expires" an
hour early, a backoff that never fires). Durations must come from
``time.monotonic()``.

Wall clock remains legitimate in exactly two places:

- **stamps that leave the process** — lease ``renew_time``,
  ``deletionTimestamp``, condition ``last_transition_time``: other
  processes compare them, so they must be wall clock by protocol;
- **logging / record keeping** — a ``wall_clock`` field on a trace
  record is data, not control flow.

Both are annotated with a scoped ``# analysis: allow-clock(<reason>)``
marker on (or directly above) the flagged line; the reason after
`` — `` documents why wall clock is semantically required.

Flagged in ``config.control_loop_modules``:

- a wall-clock call (``time.time()``, ``datetime.now()``,
  ``datetime.utcnow()``) appearing inside arithmetic (``+``/``-``) or a
  comparison — the shape of duration/timeout/expiry math;
- ``time.time`` (the function object) as an injectable-clock default —
  a parameter default or a ``clock = time.time`` class/module
  assignment — because every downstream ``self.clock() - start``
  inherits the skew sensitivity.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import FileContext, dotted_name, rule
from .findings import SEV_ERROR, Finding, allowed_rules_for_line, scoped_marker_args

# the marker slug (``# analysis: allow-clock(...)``) — deliberately the
# short form from RULES.md rather than the full rule name
MARKER = "clock"

_WALL_EXACT = {"time.time"}
_DATETIME_METHODS = {"now", "utcnow", "today"}


def _wall_call_name(func: ast.AST) -> Optional[str]:
    """The dotted name when ``func`` resolves to a wall-clock source."""
    name = dotted_name(func)
    if not name:
        return None
    if name in _WALL_EXACT:
        return name
    parts = name.split(".")
    if parts[-1] in _DATETIME_METHODS and "datetime" in parts[:-1]:
        return name
    return None


def _in_scope(ctx: FileContext) -> bool:
    rel = ctx.relpath
    return any(rel == m or rel.startswith(m) for m in ctx.config.control_loop_modules)


def _marked(ctx: FileContext, line: int) -> bool:
    """Scoped ``allow-clock(reason)`` or bare ``allow-clock`` at line."""
    if scoped_marker_args(ctx.lines, line, MARKER) is not None:
        return True
    return MARKER in allowed_rules_for_line(ctx.lines, line)


def _wall_calls_in(expr: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and _wall_call_name(node.func):
            yield node


def _clock_default_sites(nodes) -> Iterable[ast.AST]:
    """Expressions that install ``time.time`` (the function, not a call)
    as a stored/injectable clock: parameter defaults and
    ``clock = time.time``-shaped assignments. ``nodes`` is the
    file's cached preorder walk."""
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            args = node.args
            for d in list(args.defaults) + [d for d in args.kw_defaults if d]:
                if dotted_name(d) in _WALL_EXACT:
                    yield d
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None and dotted_name(node.value) in _WALL_EXACT:
                yield node.value
        elif isinstance(node, ast.Assign):
            if dotted_name(node.value) in _WALL_EXACT:
                yield node.value


@rule(
    "clock-discipline",
    "wall-clock time in control-loop duration/timeout/expiry logic "
    "(monotonic only; scoped allow-clock for persisted stamps)",
)
def clock_discipline(ctx: FileContext) -> Iterable[Finding]:
    if not _in_scope(ctx):
        return
    from .engine import qualify

    qual = None
    seen: Set[int] = set()
    findings: List[Finding] = []

    def emit(node: ast.AST, message: str) -> None:
        nonlocal qual
        if id(node) in seen:
            return
        seen.add(id(node))
        line = getattr(node, "lineno", 1)
        if _marked(ctx, line):
            return
        if qual is None:
            qual = qualify(ctx.tree)
        findings.append(
            Finding(
                rule="clock-discipline",
                path=ctx.relpath,
                line=line,
                symbol=qual.get(node, ""),
                message=message,
                severity=SEV_ERROR,
            )
        )

    # wall-clock reads participating in duration/expiry math
    for node in ctx.walk():
        if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Sub)):
            exprs: List[ast.AST] = [node.left, node.right]
        elif isinstance(node, ast.Compare):
            exprs = [node.left, *node.comparators]
        else:
            continue
        for expr in exprs:
            for call in _wall_calls_in(expr):
                name = _wall_call_name(call.func)
                emit(
                    call,
                    f"'{name}()' in duration/expiry arithmetic — wall clock "
                    f"jumps under skew/NTP step; use time.monotonic() (or a "
                    f"scoped '# analysis: allow-clock(reason)' for persisted "
                    f"wall-clock stamps)",
                )

    # wall clock installed as the injectable clock
    for site in _clock_default_sites(ctx.walk()):
        emit(
            site,
            "'time.time' installed as an injectable clock default — every "
            "downstream 'clock() - start' inherits wall-clock skew; default "
            "to time.monotonic (or mark '# analysis: allow-clock(reason)' "
            "when the stamps are persisted/cross-process by protocol)",
        )

    for f in sorted(findings, key=lambda f: f.line):
        yield f
