"""jit-registry: every jit entry point in device-hot solver modules must
register through the device-plane observatory (ISSUE 16).

``tracing/deviceplane.py`` attributes XLA recompiles to the solve that
triggered them, but only for functions routed through its seam — a
naked ``jax.jit`` / ``shard_map`` in a hot module compiles invisibly:
the zero-recompile ledger gates and the warmstore ``jitsig`` inventory
plane (the ``warmup_compile_only`` prewarmer's shopping list) both go
blind to it. Two registered forms are accepted:

- decorator form: ``@deviceplane.observe_jit("name", ...)`` stacked
  anywhere on a function that is (or wraps) jit-decorated;
- call form: the jit call is the direct argument of
  ``deviceplane.wrap("name", jax.jit(...))`` (per-call builders in
  sharding.py, where in/out shardings depend on the live mesh).

Deliberate bypasses (e.g. a throwaway jit inside a test harness helper)
carry a scoped ``# analysis: allow-jit-registry(<why>)`` marker on the
flagged line or the line above.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from .engine import FileContext, dotted_name, jit_decoration, rule
from .findings import SEV_ERROR, Finding, scoped_marker_args

#: callables whose invocation creates an XLA-compiled entry point
_JIT_CALLEES = ("jax.jit", "jit", "shard_map")


def _is_jit_registry_scoped(ctx: FileContext) -> bool:
    return any(ctx.relpath.endswith(m) for m in ctx.config.jit_registry_modules)


def _has_observe_decorator(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if dotted_name(target).endswith("observe_jit"):
            return True
    return False


def _marker_present(ctx: FileContext, lines: Iterable[int]) -> bool:
    return any(
        scoped_marker_args(ctx.lines, ln, "jit-registry") is not None for ln in lines
    )


def _jit_call_name(call: ast.Call) -> str:
    name = dotted_name(call.func)
    if name in ("jax.jit", "jit") or name.split(".")[-1] == "shard_map":
        return name
    return ""


@rule(
    "jit-registry",
    "jax.jit / shard_map entry points in device-hot solver modules must register "
    "through tracing.deviceplane (observe_jit / wrap)",
)
def check_jit_registry(ctx: FileContext):
    if not _is_jit_registry_scoped(ctx):
        return

    # nodes excused from the call-form check: jit calls living inside a
    # decorator list (the decorator-form check owns those) and jit calls
    # passed directly to deviceplane.wrap(...)
    excused: Set[ast.AST] = set()
    for node in ctx.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                excused.update(ast.walk(dec))
        elif isinstance(node, ast.Call) and dotted_name(node.func).endswith(
            "deviceplane.wrap"
        ):
            excused.update(node.args)

    symbols: List = []

    def visit(node: ast.AST, sym: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                child_sym = f"{sym}.{child.name}" if sym else child.name
                symbols.append((child, child_sym))
                visit(child, child_sym)
            else:
                visit(child, sym)

    visit(ctx.tree, "")

    for node, sym in symbols:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # decorator form: a jit-decorated function needs observe_jit in
        # the same stack (vmap alone doesn't build an executable)
        info = jit_decoration(node)
        if info is not None and info["kind"] == "jit" and not _has_observe_decorator(node):
            lines = [node.lineno] + [d.lineno for d in node.decorator_list]
            if not _marker_present(ctx, lines):
                yield Finding(
                    rule="jit-registry",
                    path=ctx.relpath,
                    line=node.decorator_list[0].lineno if node.decorator_list else node.lineno,
                    symbol=sym,
                    message=(
                        f"jit-decorated '{node.name}' bypasses the deviceplane "
                        f"registry — stack @deviceplane.observe_jit above the jit "
                        f"decorator, or mark '# analysis: allow-jit-registry(<why>)'"
                    ),
                    severity=SEV_ERROR,
                )

    # call form: bare jit/shard_map calls outside decorators must be the
    # direct argument of deviceplane.wrap
    sym_of = {id(n): s for n, s in symbols}

    def enclosing(node: ast.AST) -> str:
        return _enclosing.get(id(node), "")

    _enclosing = {}

    def mark(node: ast.AST, sym: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_sym = sym
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                child_sym = sym_of.get(id(child), sym)
            _enclosing[id(child)] = child_sym
            mark(child, child_sym)

    mark(ctx.tree, "")

    for node in ctx.walk():
        if not isinstance(node, ast.Call) or node in excused:
            continue
        name = _jit_call_name(node)
        if not name:
            continue
        if _marker_present(ctx, [node.lineno]):
            continue
        yield Finding(
            rule="jit-registry",
            path=ctx.relpath,
            line=node.lineno,
            symbol=enclosing(node),
            message=(
                f"bare '{name}(...)' call bypasses the deviceplane registry — "
                f"pass it through deviceplane.wrap(name, {name}(...)), or mark "
                f"'# analysis: allow-jit-registry(<why>)'"
            ),
            severity=SEV_ERROR,
        )
