"""Finding model, per-line suppressions, and the checked-in baseline.

A finding is identified for baseline purposes by ``(rule, path, symbol,
message)`` — line numbers are deliberately excluded so unrelated edits
above a grandfathered finding do not invalidate the baseline. Messages
are therefore written to be deterministic (no memory addresses, no
ordering artifacts).

Suppressions are per-line comments::

    x = do_risky_thing()  # analysis: allow-broad-except — why it is ok

The marker may sit on the finding's own line or the line directly above
(for statements too long to carry a trailing comment). ``# noqa: BLE001``
is honored as an alias for ``allow-broad-except`` — the repo already
uses it to annotate intentional never-die loops.
"""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"

_ALLOW_RE = re.compile(r"#\s*analysis:\s*((?:allow-[a-z0-9-]+(?:\([^)]*\))?[,\s]*)+)")
# one marker token: slug + optional parenthesized scope args. A marker
# WITH args is *scoped* — it does not blanket-suppress the rule on the
# line; the owning rule reads the args (scoped_marker_args) and decides
# per-item (cachesound's allow-cache-key(<inputs>) declares which cache
# inputs are deliberately excluded from the key, not "ignore this site").
_ALLOW_TOKEN_RE = re.compile(r"allow-([a-z0-9-]+)(?:\(([^)]*)\))?")
_NOQA_BLE_RE = re.compile(r"#\s*noqa:.*\bBLE001\b")

#: repo-native comment conventions accepted as rule suppressions, beyond
#: the canonical ``# analysis: allow-<rule>`` marker
_ALIAS_PATTERNS: Dict[str, re.Pattern] = {
    "broad-except": _NOQA_BLE_RE,
}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int  # 1-based
    symbol: str  # enclosing Class.method / function ('' = module level)
    message: str
    severity: str = SEV_ERROR

    @property
    def baseline_key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.symbol, self.message)

    def format(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{sym}"

    def to_dict(self) -> dict:
        return asdict(self)


def allowed_rules_for_line(lines: Sequence[str], line: int) -> set:
    """Rule slugs suppressed at 1-based ``line`` (its own trailing comment
    or a marker-only line directly above)."""
    out: set = set()
    for ln in (line, line - 1):
        if 1 <= ln <= len(lines):
            text = lines[ln - 1]
            m = _ALLOW_RE.search(text)
            if m:
                for tok, args in _ALLOW_TOKEN_RE.findall(m.group(1)):
                    if not args:  # scoped markers don't blanket-suppress
                        out.add(tok)
            for rule, pat in _ALIAS_PATTERNS.items():
                if pat.search(text):
                    out.add(rule)
    return out


def scoped_marker_args(
    lines: Sequence[str], line: int, rule: str
) -> Optional[List[str]]:
    """Args of a scoped ``# analysis: allow-<rule>(a, b, ...)`` marker at
    1-based ``line`` (own line or the line above), or None when the line
    carries no scoped marker for ``rule``. Args are comma/space-separated
    identifiers-or-paths; everything after `` — `` in an arg is a free-
    text reason and is dropped."""
    found: Optional[List[str]] = None
    for ln in (line, line - 1):
        if not (1 <= ln <= len(lines)):
            continue
        m = _ALLOW_RE.search(lines[ln - 1])
        if not m:
            continue
        for tok, args in _ALLOW_TOKEN_RE.findall(m.group(1)):
            if tok != rule or not args:
                continue
            out = []
            for part in re.split(r"[,\s]+", args.strip()):
                if part:
                    out.append(part)
            found = (found or []) + out
    return found


def is_suppressed(finding: Finding, lines: Sequence[str]) -> bool:
    return finding.rule in allowed_rules_for_line(lines, finding.line)


# ---------------------------------------------------------------------------
# baseline


@dataclass
class Baseline:
    """Grandfathered findings: present in the repo, acknowledged, not yet
    fixed. The gate fails on anything NOT in here; stale entries (no
    longer matching any finding) also fail so the file can only shrink
    honestly."""

    entries: List[dict] = field(default_factory=list)

    def keys(self) -> set:
        return {
            (e["rule"], e["path"], e.get("symbol", ""), e["message"])
            for e in self.entries
        }

    @staticmethod
    def load(path: str) -> "Baseline":
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
        except FileNotFoundError:
            return Baseline([])
        return Baseline(list(data.get("findings", [])))

    @staticmethod
    def from_findings(findings: Sequence[Finding], justification: str = "") -> "Baseline":
        entries = []
        for f in sorted(findings, key=lambda x: (x.path, x.line, x.rule)):
            e = {
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
            }
            if justification:
                e["justification"] = justification
            entries.append(e)
        return Baseline(entries)

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"findings": self.entries}, f, indent=2, sort_keys=False)
            f.write("\n")


def split_by_baseline(
    findings: Sequence[Finding], baseline: Optional[Baseline]
) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """→ (active, baselined, stale_baseline_entries)."""
    if baseline is None:
        return list(findings), [], []
    keys = baseline.keys()
    active = [f for f in findings if f.baseline_key not in keys]
    matched = {f.baseline_key for f in findings if f.baseline_key in keys}
    baselined = [f for f in findings if f.baseline_key in keys]
    stale = [
        e
        for e in baseline.entries
        if (e["rule"], e["path"], e.get("symbol", ""), e["message"]) not in matched
    ]
    return active, baselined, stale
