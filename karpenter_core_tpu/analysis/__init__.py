"""Repo-native static analysis (ISSUE 3).

Four AST rule families guard the invariants the test suite can only
catch by luck — lock discipline in the controller state, host<->device
sync boundaries in the solver hot path, tracer safety inside jit/vmap,
and general hygiene — plus an eval_shape-backed shape-contract verifier
for the solver's tensor functions.

Run ``python -m karpenter_core_tpu.analysis`` (AST rules, stdlib-only)
or ``--contracts`` (adds the jax.eval_shape pass). The tier-1 gate is
``tests/test_static_analysis.py``. Rule catalog: ``RULES.md`` next to
this file; per-line suppression is ``# analysis: allow-<rule>``;
grandfathered findings live in ``baseline.json``.
"""

from .engine import (  # noqa: F401
    AnalysisConfig,
    DEFAULT_CONFIG,
    Report,
    analyze_paths,
    analyze_repo,
    default_baseline_path,
    registered_rules,
    repo_root,
)
from .findings import Baseline, Finding, SEV_ERROR, SEV_WARNING  # noqa: F401
