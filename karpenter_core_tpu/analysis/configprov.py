"""Config-provenance plane (ISSUE 20): the machine-checked knob surface.

Three rule families plus the registry the runtime knob witness and the
CLI ``--knobs`` view consume:

``knob-inventory``
    AST-discovers every ``os.environ`` / ``os.getenv`` read of a
    ``KARPENTER_TPU_*`` name repo-wide into an authoritative registry —
    name, default expression, parse/clamp shape, reading module, and
    read time (import vs call). Findings: numeric parses with neither a
    ``ValueError`` guard nor a clamp (a typo'd env value must degrade to
    the default, never crash a solve), and import-time reads in
    warmstore-restorable modules (a restored process cannot re-decide
    them). Scoped escape: ``# analysis: allow-knob-inventory(NAME — why)``.

``knob-docs``
    The README "Configuration" table between ``<!-- knobs:begin -->`` /
    ``<!-- knobs:end -->`` must equal ``knob_table_lines()`` exactly —
    drift (an undocumented knob, a stale row, a hand-edited default) is
    a finding against README.md, deliberately unsuppressable.

``config-provenance``
    For every cachesound-discovered memo site, the semantic env knobs
    reachable from the cached computation's body (call-graph fixpoint
    over the shared cachesound index, ``*_token()`` helpers resolved by
    name when the receiver is opaque) must be witnessed in the key
    slice. Plus a contract table for the three historically
    read-set-invisible tokens: ``pack_engine_token`` must ride the
    pod-shard config, a ``route`` memo key must carry the
    constraint-engine token, and ``_job_key`` must keep its
    ``port_features`` / pack-engine / backend ``job_token`` components.
    Scoped escape: ``# analysis: allow-config-provenance(TOKEN — why)``.

The registry doubles as the static side of the runtime knob witness
(``analysis/knobwitness.py``): every ``KARPENTER_TPU_*`` name observed
at runtime must be in ``static_knob_names()`` (observed ⊆ static).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import (
    FileContext,
    ProjectContext,
    dotted_name,
    iter_python_files,
    parse_file,
    project_rule,
    repo_root,
    symbol_at,
)
from .findings import SEV_ERROR, Finding, scoped_marker_args

KNOB_PREFIX = "KARPENTER_TPU_"

#: knobs that select an engine / algorithm / budget and therefore change
#: memo *content*, not just performance — any memo whose body reaches one
#: of these must witness it in the key slice (or ride a ``*_token()``).
SEMANTIC_KNOBS = frozenset(
    {
        "KARPENTER_TPU_SHARD_ENGINE",
        "KARPENTER_TPU_SHARD_MIN_PODS",
        "KARPENTER_TPU_SHARDED",
        "KARPENTER_TPU_CONSTRAINT_ENGINE",
        "KARPENTER_TPU_MERGE_ENGINE",
        "KARPENTER_TPU_PACK_BACKEND",
        "KARPENTER_TPU_K_OPEN",
        "KARPENTER_TPU_LP_ITERS",
        "KARPENTER_TPU_LP_REFINE_ROUNDS",
        "KARPENTER_TPU_LP_BRANCH_K",
        "KARPENTER_TPU_COST_WEIGHTS",
        "KARPENTER_TPU_DISRUPT_ENGINE",
        "KARPENTER_TPU_FLEET_ENGINE",
    }
)


# ---------------------------------------------------------------------------
# knob registry


@dataclass(frozen=True)
class KnobSite:
    """One static read site of a ``KARPENTER_TPU_*`` name."""

    name: str  # concrete env name, or a regex when pattern=True
    pattern: bool  # dynamic (f-string) knob family
    module: str  # repo-relative path of the *reading* module (call site for helpers)
    line: int
    symbol: str
    default: str  # unparsed default expression ('' = no default)
    parse: str  # int | float | flag | enum | str
    clamp: str  # '' or e.g. 'max(1, ·)'
    guarded: bool  # a ValueError-catching try wraps the parse
    read_time: str  # 'import' | 'call'
    via: str  # helper function name for expanded sites, '' for direct


_ENV_GET = {"os.environ.get", "environ.get", "os.getenv", "getenv"}
_GUARD_EXCS = {"ValueError", "TypeError", "Exception", "BaseException", "KeyError"}


def _parents_of(ctx: FileContext) -> Dict[ast.AST, ast.AST]:
    cached = getattr(ctx, "_analysis_parents", None)
    if cached is None:
        cached = {}
        for node in ctx.walk():
            for child in ast.iter_child_nodes(node):
                cached[child] = node
        object.__setattr__(ctx, "_analysis_parents", cached)
    return cached


def _unparse(node: Optional[ast.AST]) -> str:
    if node is None:
        return ""
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — registry rendering must never crash the rule
        return "<expr>"


def _catches_value_error(t: ast.Try) -> bool:
    for h in t.handlers:
        if h.type is None:
            return True
        names = []
        if isinstance(h.type, ast.Tuple):
            names = [dotted_name(e) for e in h.type.elts]
        else:
            names = [dotted_name(h.type)]
        if any(n.split(".")[-1] in _GUARD_EXCS for n in names):
            return True
    return False


def _read_shape(
    ctx: FileContext, node: ast.AST
) -> Tuple[str, str, bool, str, Optional[ast.AST]]:
    """(parse, clamp, guarded, read_time, enclosing_fn) for an env-read
    call node, from its ancestor chain up to the enclosing scope."""
    parents = _parents_of(ctx)
    parse = "str"
    clamps: List[str] = []
    guarded = False
    enclosing: Optional[ast.AST] = None
    cur: ast.AST = node
    p = parents.get(cur)
    hops = 0
    while p is not None and hops < 40:
        hops += 1
        if isinstance(p, ast.Call):
            base = dotted_name(p.func).split(".")[-1]
            if base in ("int", "float") and parse == "str":
                parse = base
            elif base in ("max", "min"):
                bound = next(
                    (a for a in p.args if a is not cur and not isinstance(a, ast.Starred)),
                    None,
                )
                clamps.append(f"{base}({_unparse(bound)}, ·)")
        elif isinstance(p, ast.Compare) and parse == "str":
            parse = "flag"
        elif isinstance(p, ast.Attribute) and p.attr in ("strip", "lower", "upper"):
            if parse == "str":
                parse = "enum"
        elif isinstance(p, ast.Try) and _catches_value_error(p):
            guarded = True
        elif isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            enclosing = p
            break
        cur, p = p, parents.get(p)
    read_time = "call" if enclosing is not None else "import"
    return parse, " ".join(clamps), guarded, read_time, enclosing


def _module_consts(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "KARPENTER_TPU_..."`` string constants."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _module_dicts(tree: ast.Module) -> Dict[str, List[Tuple[List[str], List[ast.AST]]]]:
    """Module-level dicts whose values are tuples carrying env names —
    ``_CAPS = {"route": ("KARPENTER_TPU_ROUTE_CACHE_MAX", 512), ...}``.
    Maps dict name → list of (tuple-elt strings-or-'', tuple-elt nodes)."""
    out: Dict[str, List[Tuple[List[str], List[ast.AST]]]] = {}
    for stmt in tree.body:
        if not (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Dict)
        ):
            continue
        rows: List[Tuple[List[str], List[ast.AST]]] = []
        for v in stmt.value.values:
            if isinstance(v, ast.Tuple):
                strs = [
                    e.value if isinstance(e, ast.Constant) and isinstance(e.value, str) else ""
                    for e in v.elts
                ]
                rows.append((strs, list(v.elts)))
        if rows:
            out[stmt.targets[0].id] = rows
    return out


def _env_read_call(node: ast.AST) -> Optional[Tuple[ast.AST, Optional[ast.AST]]]:
    """(name_expr, default_expr) when ``node`` reads the environment."""
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in _ENV_GET and node.args:
            default = node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "default":
                    default = kw.value
            return node.args[0], default
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if dotted_name(node.value) in ("os.environ", "environ"):
            return node.slice, None
    return None


def _fn_params(fn: Optional[ast.AST]) -> List[str]:
    if fn is None or isinstance(fn, ast.Lambda):
        args = fn.args if fn is not None else None
    else:
        args = fn.args
    if args is None:
        return []
    return [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]


@dataclass
class _Helper:
    """A function reading the env through a parameter-supplied name."""

    fn_name: str
    module: str
    param: str
    param_index: int
    default_index: Optional[int]  # positional index of a 'default' param
    template: str  # '' for plain helpers, 'KARPENTER_TPU_X_{}_Y' for f-string ones
    upper: bool  # the placeholder is .upper()'d
    parse: str
    clamp: str
    guarded: bool
    read_default: str  # default expr at the read site ('' when param-supplied)


def _tuple_unpack_sites(
    fn: ast.AST, name: str, dicts: Dict[str, List[Tuple[List[str], List[ast.AST]]]]
) -> Optional[List[Tuple[str, str]]]:
    """Resolve ``env, default = _CAPS[key]``-style names: when ``name``
    is tuple-unpacked from a module dict inside ``fn``, return the
    (env_name, default_expr) expansion over every dict row."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Tuple):
            continue
        idx = next(
            (i for i, e in enumerate(tgt.elts) if isinstance(e, ast.Name) and e.id == name),
            None,
        )
        if idx is None:
            continue
        if not (
            isinstance(node.value, ast.Subscript)
            and isinstance(node.value.value, ast.Name)
            and node.value.value.id in dicts
        ):
            continue
        out: List[Tuple[str, str]] = []
        for strs, elts in dicts[node.value.value.id]:
            if idx < len(strs) and strs[idx].startswith(KNOB_PREFIX):
                default = _unparse(elts[1]) if idx == 0 and len(elts) > 1 else ""
                out.append((strs[idx], default))
        if out:
            return out
    return None


def _joined_template(
    expr: ast.JoinedStr, params: Sequence[str]
) -> Optional[Tuple[str, bool, bool]]:
    """(template, references_param, upper) for an f-string env name.
    Placeholders become ``{}``; returns None when the literal part does
    not carry the knob prefix."""
    parts: List[str] = []
    references_param = False
    upper = False
    for v in expr.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        elif isinstance(v, ast.FormattedValue):
            parts.append("{}")
            inner = v.value
            if (
                isinstance(inner, ast.Call)
                and isinstance(inner.func, ast.Attribute)
                and inner.func.attr == "upper"
            ):
                upper = True
                inner = inner.func.value
            if isinstance(inner, ast.Name) and inner.id in params:
                references_param = True
    template = "".join(parts)
    if not template.startswith(KNOB_PREFIX):
        return None
    return template, references_param, upper


def _module_def_and_imported_names(ctx: FileContext) -> Set[str]:
    out: Set[str] = set()
    for node in ctx.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                out.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                out.add((a.asname or a.name).split(".")[0])
    return out


def build_registry(files: Sequence[FileContext]) -> Dict[str, List[KnobSite]]:
    """The authoritative knob registry over ``files`` — two passes:
    direct reads (constants / module constants / dict-unpacks /
    f-strings), then expansion of parameter-name helper reads at their
    constant-argument call sites."""
    sites: List[KnobSite] = []
    helpers: Dict[str, _Helper] = {}
    symcaches: Dict[str, dict] = {}

    def add(ctx: FileContext, node: ast.AST, **kw) -> None:
        sites.append(
            KnobSite(
                module=ctx.relpath,
                line=node.lineno,
                symbol=symbol_at(ctx.tree, node, symcaches.setdefault(ctx.relpath, {})),
                **kw,
            )
        )

    for ctx in files:
        consts = _module_consts(ctx.tree)
        dicts = _module_dicts(ctx.tree)
        for node in ctx.walk():
            read = _env_read_call(node)
            if read is None:
                continue
            name_expr, default_expr = read
            parse, clamp, guarded, read_time, enclosing = _read_shape(ctx, node)
            params = _fn_params(enclosing)
            common = dict(
                pattern=False,
                default=_unparse(default_expr),
                parse=parse,
                clamp=clamp,
                guarded=guarded,
                read_time=read_time,
                via="",
            )
            if isinstance(name_expr, ast.Constant) and isinstance(name_expr.value, str):
                if name_expr.value.startswith(KNOB_PREFIX):
                    add(ctx, node, name=name_expr.value, **common)
            elif isinstance(name_expr, ast.Name):
                nm = name_expr.id
                if nm in consts:
                    if consts[nm].startswith(KNOB_PREFIX):
                        add(ctx, node, name=consts[nm], **common)
                elif nm in params and isinstance(
                    enclosing, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    plist = _fn_params(enclosing)
                    didx = next(
                        (i for i, a in enumerate(plist) if a == "default"), None
                    )
                    helpers[enclosing.name] = _Helper(
                        fn_name=enclosing.name,
                        module=ctx.relpath,
                        param=nm,
                        param_index=plist.index(nm),
                        default_index=didx,
                        template="",
                        upper=False,
                        parse=parse,
                        clamp=clamp,
                        guarded=guarded,
                        read_default=_unparse(default_expr),
                    )
                elif enclosing is not None:
                    rows = _tuple_unpack_sites(enclosing, nm, dicts)
                    if rows:
                        via = (
                            enclosing.name
                            if isinstance(
                                enclosing, (ast.FunctionDef, ast.AsyncFunctionDef)
                            )
                            else ""
                        )
                        for env_name, row_default in rows:
                            kw = dict(common)
                            kw["default"] = row_default or kw["default"]
                            kw["via"] = via
                            add(ctx, node, name=env_name, **kw)
            elif isinstance(name_expr, ast.JoinedStr):
                t = _joined_template(name_expr, params)
                if t is None:
                    continue
                template, references_param, upper = t
                if references_param and isinstance(
                    enclosing, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    plist = _fn_params(enclosing)
                    pname = next(p for p in plist)  # refined below
                    # find the referenced param precisely
                    for v in name_expr.values:
                        if isinstance(v, ast.FormattedValue):
                            inner = v.value
                            if isinstance(inner, ast.Call) and isinstance(
                                inner.func, ast.Attribute
                            ):
                                inner = inner.func.value
                            if isinstance(inner, ast.Name) and inner.id in plist:
                                pname = inner.id
                    didx = next(
                        (i for i, a in enumerate(plist) if a == "default"), None
                    )
                    helpers[enclosing.name] = _Helper(
                        fn_name=enclosing.name,
                        module=ctx.relpath,
                        param=pname,
                        param_index=plist.index(pname),
                        default_index=didx,
                        template=template,
                        upper=upper,
                        parse=parse,
                        clamp=clamp,
                        guarded=guarded,
                        read_default=_unparse(default_expr),
                    )
                else:
                    add(
                        ctx,
                        node,
                        name=re.escape(template).replace(r"\{\}", "[A-Z0-9_]+"),
                        **{**common, "pattern": True},
                    )

    # pass 2: expand helper calls with resolvable name arguments
    if helpers:
        for ctx in files:
            visible: Optional[Set[str]] = None  # computed lazily: most files call no helper
            consts = _module_consts(ctx.tree)
            for node in ctx.walk():
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func).split(".")[-1]
                h = helpers.get(fname)
                if h is None:
                    continue
                if h.module != ctx.relpath:
                    if visible is None:
                        visible = _module_def_and_imported_names(ctx)
                    if fname not in visible:
                        continue
                arg: Optional[ast.AST] = None
                if h.param_index < len(node.args):
                    arg = node.args[h.param_index]
                for kw in node.keywords:
                    if kw.arg == h.param:
                        arg = kw.value
                val: Optional[str] = None
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    val = arg.value
                elif isinstance(arg, ast.Name) and arg.id in consts:
                    val = consts[arg.id]
                default = h.read_default
                if h.default_index is not None and h.default_index < len(node.args):
                    default = _unparse(node.args[h.default_index])
                for kw in node.keywords:
                    if kw.arg == "default":
                        default = _unparse(kw.value)
                _, _, _, read_time, _ = _read_shape(ctx, node)
                common = dict(
                    default=default,
                    parse=h.parse,
                    clamp=h.clamp,
                    guarded=h.guarded,
                    read_time=read_time,
                    via=h.fn_name,
                )
                if val is not None:
                    name = (
                        h.template.format(val.upper() if h.upper else val)
                        if h.template
                        else val
                    )
                    if name.startswith(KNOB_PREFIX):
                        add(ctx, node, name=name, pattern=False, **common)
                elif h.template:
                    add(
                        ctx,
                        node,
                        name=re.escape(h.template).replace(r"\{\}", "[A-Z0-9_]+"),
                        pattern=True,
                        **common,
                    )

    registry: Dict[str, List[KnobSite]] = {}
    for s in sites:
        registry.setdefault(s.name, []).append(s)
    for name in registry:
        registry[name] = sorted(registry[name], key=lambda s: (s.module, s.line))
    return dict(sorted(registry.items()))


def _package_files(
    root: str, pctx: Optional[ProjectContext] = None
) -> List[FileContext]:
    """Every package module loaded through the shared parse cache —
    the registry source for full runs, ``--changed-only`` runs (which
    must still see the whole knob surface), the witness, and the CLI.
    With a ``pctx``, contexts are shared with the run (walk memos and
    the cachesound index reuse them)."""
    from .engine import DEFAULT_CONFIG

    pkg = os.path.join(root, "karpenter_core_tpu")
    out: List[FileContext] = []
    for path in iter_python_files([pkg]):
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
        rel = rel.replace(os.sep, "/")
        if pctx is not None:
            ctx = pctx.get(rel)
            if ctx is not None:
                out.append(ctx)
            continue
        try:
            source, tree = parse_file(path)
        except (OSError, SyntaxError, UnicodeDecodeError):
            continue
        out.append(FileContext(rel, source, source.splitlines(), tree, DEFAULT_CONFIG))
    return out


def _shared_registry(pctx: ProjectContext) -> Dict[str, List[KnobSite]]:
    """The knob registry for a project run — package modules plus
    fixture files (snippets opt in by living outside the package), built
    once per ProjectContext (knob-inventory and knob-docs share it)."""
    cached = getattr(pctx, "_configprov_registry", None)
    if cached is not None:
        return cached
    files: Dict[str, FileContext] = {}
    for ctx in _package_files(pctx.root, pctx):
        files[ctx.relpath] = ctx
    for ctx in pctx.files:
        if not ctx.relpath.startswith("karpenter_core_tpu/"):
            files[ctx.relpath] = ctx
    registry = build_registry(list(files.values()))
    pctx._configprov_registry = registry
    pctx._configprov_files = files
    return registry


def _package_registry(pctx: ProjectContext) -> Dict[str, List[KnobSite]]:
    """Package-only registry (no fixture opt-ins): what the README table
    and the runtime witness are checked against."""
    cached = getattr(pctx, "_configprov_pkg_registry", None)
    if cached is not None:
        return cached
    pkg_files = _package_files(pctx.root, pctx)
    full = getattr(pctx, "_configprov_files", None)
    if full is not None and len(full) == len(pkg_files):
        registry = _shared_registry(pctx)  # no fixtures in this run: same set
    else:
        registry = build_registry(pkg_files)
    pctx._configprov_pkg_registry = registry
    return registry


def repo_registry(root: Optional[str] = None) -> Dict[str, List[KnobSite]]:
    return build_registry(_package_files(root or repo_root()))


def static_knob_names(
    root: Optional[str] = None,
) -> Tuple[Set[str], List["re.Pattern[str]"]]:
    """(concrete names, compiled patterns) — the witness's static side."""
    names: Set[str] = set()
    patterns: List[re.Pattern[str]] = []
    for name, sites in repo_registry(root).items():
        if any(s.pattern for s in sites):
            patterns.append(re.compile(f"^{name}$"))
        else:
            names.add(name)
    return names, patterns


# ---------------------------------------------------------------------------
# --knobs rendering (the README table IS this output)


def _shorten(module: str) -> str:
    return module[len("karpenter_core_tpu/") :] if module.startswith(
        "karpenter_core_tpu/"
    ) else module


def knob_rows(registry: Dict[str, List[KnobSite]]) -> List[dict]:
    rows = []
    for name, sites in sorted(registry.items()):
        first = sites[0]
        numeric = next((s for s in sites if s.parse in ("int", "float")), None)
        lead = numeric or first
        shape = lead.parse
        if lead.clamp:
            shape += f" · {lead.clamp}"
        if lead.guarded:
            shape += " · guarded"
        defaults = []
        for s in sites:
            if s.default and s.default not in defaults:
                defaults.append(s.default)
        rows.append(
            {
                "name": name,
                "pattern": any(s.pattern for s in sites),
                "default": "; ".join(defaults),
                "shape": shape,
                "read": "import" if any(s.read_time == "import" for s in sites) else "call",
                "modules": sorted({_shorten(s.module) for s in sites}),
                "sites": [
                    {
                        "module": s.module,
                        "line": s.line,
                        "symbol": s.symbol,
                        "via": s.via,
                        "read_time": s.read_time,
                        "guarded": s.guarded,
                        "clamp": s.clamp,
                        "parse": s.parse,
                        "default": s.default,
                    }
                    for s in sites
                ],
            }
        )
    return rows


def knob_table_lines(registry: Dict[str, List[KnobSite]]) -> List[str]:
    """The markdown knob table — identical bytes in ``--knobs`` output
    and the README block, so drift is a string comparison."""
    out = [
        "| Knob | Default | Shape | Read | Where |",
        "| --- | --- | --- | --- | --- |",
    ]
    for row in knob_rows(registry):
        name = row["name"].replace("\\", "") if row["pattern"] else row["name"]
        if row["pattern"]:
            name = name.replace("[A-Z0-9_]+", "<NAME>")
        default = f"`{row['default']}`" if row["default"] else "—"
        out.append(
            "| `{}` | {} | {} | {} | {} |".format(
                name,
                default,
                row["shape"],
                row["read"],
                ", ".join(f"`{m}`" for m in row["modules"]),
            )
        )
    return out


KNOBS_BEGIN = "<!-- knobs:begin (generated: python -m karpenter_core_tpu.analysis --knobs) -->"
KNOBS_END = "<!-- knobs:end -->"


# ---------------------------------------------------------------------------
# knob-inventory findings


def _restorable(relpath: str, config) -> bool:
    return any(relpath.endswith(m) for m in config.restorable_modules)


@project_rule(
    "knob-inventory",
    "every KARPENTER_TPU_* env read is registered; numeric parses are guarded or clamped; no import-time reads in warmstore-restorable modules",
)
def check_knob_inventory(pctx: ProjectContext) -> Iterable[Finding]:
    registry = _shared_registry(pctx)
    files: Dict[str, FileContext] = pctx._configprov_files

    def allowed(ctx: Optional[FileContext], line: int, token: str) -> bool:
        if ctx is None:
            return False
        args = scoped_marker_args(ctx.lines, line, "knob-inventory")
        return bool(args) and token in args

    for name, sites in registry.items():
        for s in sites:
            ctx = files.get(s.module)
            token = name if not s.pattern else (s.via or name)
            if (
                s.parse in ("int", "float")
                and not s.guarded
                and not s.clamp
                and not allowed(ctx, s.line, token)
            ):
                yield Finding(
                    rule="knob-inventory",
                    path=s.module,
                    line=s.line,
                    symbol=s.symbol,
                    message=(
                        f"unguarded {s.parse}() parse of {token}: a typo'd env "
                        f"value crashes the reader — wrap in try/except "
                        f"ValueError (fall back to the default) or clamp, or "
                        f"declare `# analysis: allow-knob-inventory({token} — why)`"
                    ),
                    severity=SEV_ERROR,
                )
            if (
                s.read_time == "import"
                and _restorable(s.module, pctx.config)
                and not allowed(ctx, s.line, token)
            ):
                yield Finding(
                    rule="knob-inventory",
                    path=s.module,
                    line=s.line,
                    symbol=s.symbol,
                    message=(
                        f"import-time read of {token} in a warmstore-restorable "
                        f"module: a restored process can never re-decide it — "
                        f"move the read behind a function, or declare "
                        f"`# analysis: allow-knob-inventory({token} — why)`"
                    ),
                    severity=SEV_ERROR,
                )


@project_rule(
    "knob-docs",
    "the README Configuration table equals the generated knob registry (python -m karpenter_core_tpu.analysis --knobs)",
)
def check_knob_docs(pctx: ProjectContext) -> Iterable[Finding]:
    readme = os.path.join(pctx.root, "README.md")
    try:
        with open(readme, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return  # fixture roots carry no README: nothing to drift
    if KNOBS_BEGIN not in text or KNOBS_END not in text:
        yield Finding(
            rule="knob-docs",
            path="README.md",
            line=1,
            symbol="",
            message=(
                f"README has no generated knob table (missing '{KNOBS_BEGIN}' "
                f"markers) — add a Configuration section holding the output of "
                f"`python -m karpenter_core_tpu.analysis --knobs`"
            ),
            severity=SEV_ERROR,
        )
        return
    block = text.split(KNOBS_BEGIN, 1)[1].split(KNOBS_END, 1)[0]
    documented = [ln for ln in block.splitlines() if ln.strip()]
    generated = knob_table_lines(_package_registry(pctx))
    if documented == generated:
        return
    line = text[: text.index(KNOBS_BEGIN)].count("\n") + 1
    doc_names = {ln.split("|")[1].strip() for ln in documented if ln.startswith("| `")}
    gen_names = {ln.split("|")[1].strip() for ln in generated if ln.startswith("| `")}
    undocumented = sorted(n.strip("`") for n in gen_names - doc_names)
    stale = sorted(n.strip("`") for n in doc_names - gen_names)
    detail = []
    if undocumented:
        detail.append("undocumented: " + ", ".join(undocumented))
    if stale:
        detail.append("stale rows: " + ", ".join(stale))
    if not detail:
        drift = next(
            (i for i, (a, b) in enumerate(zip(documented, generated)) if a != b),
            min(len(documented), len(generated)),
        )
        detail.append(f"row {drift + 1} drifted")
    yield Finding(
        rule="knob-docs",
        path="README.md",
        line=line,
        symbol="",
        message=(
            "README knob table drifted from the code registry ("
            + "; ".join(detail)
            + ") — regenerate with `python -m karpenter_core_tpu.analysis --knobs`"
        ),
        severity=SEV_ERROR,
    )


# ---------------------------------------------------------------------------
# config-provenance: memo bodies' env reads must ride the key


def _direct_env_names(mi, fn_node: ast.AST) -> Set[str]:
    consts = getattr(mi, "_configprov_consts", None)
    if consts is None:
        consts = _module_consts(mi.ctx.tree)
        mi._configprov_consts = consts
    out: Set[str] = set()
    for node in ast.walk(fn_node):
        read = _env_read_call(node)
        if read is None:
            continue
        name_expr, _ = read
        if isinstance(name_expr, ast.Constant) and isinstance(name_expr.value, str):
            if name_expr.value.startswith(KNOB_PREFIX):
                out.add(name_expr.value)
        elif isinstance(name_expr, ast.Name) and name_expr.id in consts:
            if consts[name_expr.id].startswith(KNOB_PREFIX):
                out.add(consts[name_expr.id])
    return out


def _reads_env_via_param(fn_node: ast.AST) -> bool:
    params = set(_fn_params(fn_node))
    for node in ast.walk(fn_node):
        read = _env_read_call(node)
        if read is None:
            continue
        name_expr, _ = read
        if isinstance(name_expr, ast.Name) and name_expr.id in params:
            return True
        if isinstance(name_expr, ast.JoinedStr):
            return True
    return False


class _EnvReach:
    """Fixpoint of KARPENTER_TPU_* names reachable from a function
    through the cachesound cross-module call graph. ``*_token()`` calls
    whose receiver is opaque resolve by name across every indexed
    module — the declared token grammar that lets key helpers ride."""

    def __init__(self, an) -> None:
        self.an = an
        self._memo: Dict[int, Set[str]] = {}
        self._stack: Set[int] = set()
        self._by_name: Dict[str, List] = {}
        for mi in an.modules.values():
            for fname, fi in mi.functions.items():
                self._by_name.setdefault(fname, []).append(fi)
            for ci in mi.classes.values():
                for mname, fi in ci.methods.items():
                    self._by_name.setdefault(mname, []).append(fi)

    def _module_of(self, fi):
        return self.an.modules.get(fi.ctx.relpath)

    def of(self, fi) -> Set[str]:
        key = id(fi.node)
        if key in self._memo:
            return self._memo[key]
        if key in self._stack:
            return set()
        self._stack.add(key)
        mi = self._module_of(fi)
        out: Set[str] = set()
        if mi is not None:
            out |= _direct_env_names(mi, fi.node)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                out |= self.of_call(node, fi)
            elif (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)
            ):
                pi = self.an.resolve_property("self", node.attr, fi)
                if pi is not None:
                    out |= self.of(pi)
        self._stack.discard(key)
        self._memo[key] = out
        return out

    def of_call(self, call: ast.Call, fi) -> Set[str]:
        out: Set[str] = set()
        target = self.an.resolve_call(call, fi)
        if target is not None:
            out |= self.of(target)
            if _reads_env_via_param(target.node):
                for a in list(call.args) + [k.value for k in call.keywords]:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        if a.value.startswith(KNOB_PREFIX):
                            out.add(a.value)
        else:
            base = dotted_name(call.func).split(".")[-1]
            if base.endswith("_token"):
                for cand in self._by_name.get(base, []):
                    out |= self.of(cand)
        return out


def _assign_map(fn_node: ast.AST) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}

    def record(target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            out.setdefault(target.id, []).append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                record(e, value)

    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                record(t, node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            record(node.target, node.value)
        elif isinstance(node, ast.AugAssign):
            record(node.target, node.value)
        elif isinstance(node, ast.NamedExpr):
            record(node.target, node.value)
        elif isinstance(node, ast.For):
            record(node.target, node.iter)
    return out


def _slice_closure(fn_node: ast.AST, seeds: Sequence[ast.AST]) -> List[ast.AST]:
    """Def-use closure of ``seeds`` over the function's assignments —
    the 'key slice' / 'body slice' the provenance comparison runs on."""
    assigns = _assign_map(fn_node)
    out: List[ast.AST] = []
    seen: Set[int] = set()
    work = list(seeds)
    while work and len(out) < 400:
        n = work.pop()
        if n is None or id(n) in seen:
            continue
        seen.add(id(n))
        out.append(n)
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                for rhs in assigns.get(sub.id, []):
                    if id(rhs) not in seen:
                        work.append(rhs)
    return out


def _env_of_slice(reach: _EnvReach, fi, nodes: Sequence[ast.AST]) -> Set[str]:
    an = reach.an
    mi = an.modules.get(fi.ctx.relpath)
    out: Set[str] = set()
    for n in nodes:
        for sub in ast.walk(n):
            read = _env_read_call(sub)
            if read is not None and mi is not None:
                name_expr, _ = read
                if isinstance(name_expr, ast.Constant) and isinstance(
                    name_expr.value, str
                ):
                    if name_expr.value.startswith(KNOB_PREFIX):
                        out.add(name_expr.value)
            if isinstance(sub, ast.Call):
                out |= reach.of_call(sub, fi)
            elif (
                isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and isinstance(sub.ctx, ast.Load)
            ):
                pi = an.resolve_property("self", sub.attr, fi)
                if pi is not None:
                    out |= reach.of(pi)
    return out


def _prov_allowed(fi, line: int, token: str) -> bool:
    for ln in (line, fi.node.lineno):
        args = scoped_marker_args(fi.ctx.lines, ln, "config-provenance")
        if args and token in args:
            return True
    return False


def _calls_named(fn_node: ast.AST, name: str) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            if dotted_name(node.func).split(".")[-1] == name:
                return True
    return False


def _subscripts_const(fn_node: ast.AST, key: str) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Subscript):
            sl = node.slice
            if isinstance(sl, ast.Constant) and sl.value == key:
                return True
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == key
            ):
                return True
    return False


#: the three historically read-set-invisible tokens (RULES.md residual
#: entry, retired by this rule): function name → required body elements.
_TOKEN_CONTRACTS: Tuple[Tuple[str, Tuple[Tuple[str, str, str], ...]], ...] = (
    (
        "pack_engine_token",
        (
            (
                "call",
                "pod_shard_token",
                "pack_engine_token dropped the pod-shard config: shard-mode "
                "plans differ per chunking, so a job memo keyed without "
                "pod_shard_token(mesh) serves a stale plan across "
                "KARPENTER_TPU_SHARD_* flips",
            ),
        ),
    ),
    (
        "_job_key",
        (
            (
                "subscript",
                "port_features",
                "_job_key dropped the port_features component: hostPort-"
                "constrained pods pack differently, so two catalogs differing "
                "only in port usage would alias one memo row",
            ),
            (
                "call",
                "pack_engine_token",
                "_job_key dropped pack_engine_token: the job memo no longer "
                "witnesses the pack-engine/native/shard config and a restored "
                "process replays plans from a different engine",
            ),
            (
                "call",
                "job_token",
                "_job_key dropped the backend job_token: LP-backend budget "
                "knobs (iters/refine/branch) change plan content and must "
                "ride the key",
            ),
        ),
    ),
)


@project_rule(
    "config-provenance",
    "every env knob reachable from a memoized computation's body is witnessed in its key slice (or rides a declared *_token helper)",
)
def check_config_provenance(pctx: ProjectContext) -> Iterable[Finding]:
    from .cachesound import _shared_analyzer, _shared_sites

    an = _shared_analyzer(pctx)
    reach = _EnvReach(an)

    def finding(fi, line: int, msg: str) -> Finding:
        return Finding(
            rule="config-provenance",
            path=fi.ctx.relpath,
            line=line,
            symbol=fi.symbol,
            message=msg,
            severity=SEV_ERROR,
        )

    # contract table: the named key helpers must keep their token rides
    for mi in an.modules.values():
        fns = dict(mi.functions)
        for ci in mi.classes.values():
            fns.update(ci.methods)
        for fname, fi in fns.items():
            for contract_fn, requirements in _TOKEN_CONTRACTS:
                if fname != contract_fn:
                    continue
                for kind, token, msg in requirements:
                    present = (
                        _calls_named(fi.node, token)
                        if kind == "call"
                        else _subscripts_const(fi.node, token)
                    )
                    if not present and not _prov_allowed(fi, fi.node.lineno, token):
                        yield finding(
                            fi,
                            fi.node.lineno,
                            msg
                            + f" — restore the {token} component or declare "
                            f"`# analysis: allow-config-provenance({token} — why)`",
                        )

    # per-site: body env reads ⊆ key env witness
    for site in _shared_sites(an).values():
        if not site.puts:
            continue
        fi = site.fn
        key_seeds = [e for ev in site.gets + site.puts for e in ev.key_exprs]
        val_seeds = [e for ev in site.puts for e in ev.value_exprs]
        anchor = min(ev.line for ev in site.puts)
        key_slice = _slice_closure(fi.node, key_seeds)
        key_env = _env_of_slice(reach, fi, key_slice)
        body_env = _env_of_slice(reach, fi, _slice_closure(fi.node, val_seeds))
        if site.spec.name == "route":
            # the route memo's constraint-engine token contract: the key
            # slice must carry a constraint_engine() call even when the
            # value slice's env reach is opaque (engine dispatch happens
            # behind per-group helpers)
            has_ce = any(
                isinstance(sub, ast.Call)
                and dotted_name(sub.func).split(".")[-1] == "constraint_engine"
                for n in key_slice
                for sub in ast.walk(n)
            )
            if not has_ce and not _prov_allowed(fi, anchor, "constraint_engine"):
                yield finding(
                    fi,
                    anchor,
                    "route memo key never witnesses the constraint-engine "
                    "token: tensor- and host-engine position lists differ in "
                    "tie-break order, so a KARPENTER_TPU_CONSTRAINT_ENGINE "
                    "flip would replay the other engine's plan — append "
                    '(("ce", constraint_engine()),) to the key or declare '
                    "`# analysis: allow-config-provenance(constraint_engine — why)`",
                )
        for name in sorted((body_env & SEMANTIC_KNOBS) - key_env):
            if _prov_allowed(fi, anchor, name):
                continue
            yield finding(
                fi,
                anchor,
                f"memoized computation reads {name} but the memo key never "
                f"witnesses it: a process with a different {name} replays "
                f"this entry verbatim — thread the knob (or a *_token() "
                f"helper reading it) into the key, or declare "
                f"`# analysis: allow-config-provenance({name} — why)`",
            )
