"""Iteration-order determinism (ISSUE 20): unordered producers must not
feed order-bearing outputs in the solver/fleet/native/warmstore planes.

Plans, fingerprints/stable hashes, and warmstore payloads all cross a
process boundary; anything order-unstable that reaches them breaks the
repo's plan-identity invariant in exactly the way the PR-5
``_selector_keys`` sort and PR-8 stable argmin tie-breaks hand-fixed.
This rule generalizes those fixes:

- ``os.listdir`` / ``glob.glob`` / ``glob.iglob`` / ``os.scandir`` not
  wrapped in ``sorted(...)`` — filesystem enumeration order is
  arbitrary across kernels and filesystems.
- bare ``.popitem()`` — pops the *last* item, an insertion-order
  artifact; ``popitem(last=False)`` (FIFO eviction) is the repo idiom
  and stays clean.
- iterating a set produced in-expression (``for x in {...}``,
  ``tuple(set(...))``) without ``sorted(...)`` — PYTHONHASHSEED
  reorders sets across processes.
- ``.items()`` / ``.keys()`` / ``.values()`` or set producers feeding a
  ``stable_hash`` / ``*fingerprint*`` / ``*digest*`` call (through the
  local def-use slice) without ``sorted(...)`` — dict insertion order
  is deterministic in-process but *arrival-order-bearing*, which is
  exactly what a content digest must normalize away.

Deliberate order-bearing walks (e.g. warmstore's LRU payload emission,
where recency order IS the payload semantics) declare a scoped
``# analysis: allow-determinism(<why>)`` marker — the rationale is
mandatory; a bare marker still blanket-suppresses but review rejects it.

Dict iteration outside hash sinks is NOT flagged: insertion order is
deterministic for a single process's plan emission.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set

from .engine import FileContext, dotted_name, rule
from .findings import SEV_ERROR, Finding, scoped_marker_args

_FS_PRODUCERS = {"os.listdir", "listdir", "glob.glob", "glob.iglob", "os.scandir", "scandir"}
_HASH_SINKS = ("stable_hash", "fingerprint", "digest")
_ORDER_METHODS = {"items", "keys", "values"}


def _in_scope(ctx: FileContext) -> bool:
    if not ctx.relpath.startswith("karpenter_core_tpu/"):
        return True  # fixture opt-in (same convention as ProjectContext.matching)
    return any(
        ctx.relpath.startswith(p) or ctx.relpath == p
        for p in ctx.config.determinism_prefixes
    )


def _parents_of(ctx: FileContext) -> Dict[ast.AST, ast.AST]:
    cached = getattr(ctx, "_analysis_parents", None)
    if cached is None:
        cached = {}
        for node in ctx.walk():
            for child in ast.iter_child_nodes(node):
                cached[child] = node
        object.__setattr__(ctx, "_analysis_parents", cached)
    return cached


def _under_sorted(ctx: FileContext, node: ast.AST) -> bool:
    parents = _parents_of(ctx)
    cur: Optional[ast.AST] = node
    for _ in range(12):
        cur = parents.get(cur)
        if cur is None:
            return False
        if isinstance(cur, ast.Call):
            base = dotted_name(cur.func).split(".")[-1]
            if base in ("sorted", "min", "max", "sum", "len", "set", "frozenset", "Counter"):
                return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
    return False


def _allowed(ctx: FileContext, line: int) -> bool:
    return scoped_marker_args(ctx.lines, line, "determinism") is not None


def _is_set_producer(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        base = dotted_name(node.func).split(".")[-1]
        return base in ("set", "frozenset")
    return False


def _finding(ctx: FileContext, node: ast.AST, symbols: dict, msg: str) -> Finding:
    from .engine import symbol_at

    return Finding(
        rule="determinism",
        path=ctx.relpath,
        line=node.lineno,
        symbol=symbol_at(ctx.tree, node, symbols),
        message=msg,
        severity=SEV_ERROR,
    )


def _assign_map(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, []).append(node.value)
        elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
            out.setdefault(node.target.id, []).append(node.value)
    return out


def _hash_sink_slice(
    fn_node: ast.AST, call: ast.Call
) -> List[ast.AST]:
    """Def-use closure of a hash-sink call's arguments within the
    enclosing function — the material the digest actually covers."""
    assigns = _assign_map(fn_node)
    out: List[ast.AST] = []
    seen: Set[int] = set()
    work: List[ast.AST] = list(call.args) + [k.value for k in call.keywords]
    while work and len(out) < 300:
        n = work.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        out.append(n)
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                for rhs in assigns.get(sub.id, []):
                    if id(rhs) not in seen:
                        work.append(rhs)
    return out


@rule(
    "determinism",
    "no unordered producers (unsorted listdir/glob, bare popitem, set iteration) feeding plans, digests, or warmstore payloads",
)
def check_determinism(ctx: FileContext) -> Iterable[Finding]:
    if not _in_scope(ctx):
        return
    symbols: dict = {}
    flagged: Set[int] = set()

    def emit(node: ast.AST, msg: str):
        if id(node) in flagged:
            return None
        flagged.add(id(node))
        return _finding(ctx, node, symbols, msg)

    # hash/fingerprint sinks first: their slices flag dict-order material
    # that the producer checks below deliberately leave alone
    has_sinks = any(s in ctx.source for s in _HASH_SINKS)
    parents = _parents_of(ctx) if has_sinks else {}

    def _host_fn(node: ast.AST) -> Optional[ast.AST]:
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = parents.get(cur)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
        return None

    for node in ctx.walk() if has_sinks else ():
        if not isinstance(node, ast.Call):
            continue
        base = dotted_name(node.func).split(".")[-1]
        if not (base == "stable_hash" or any(s in base for s in _HASH_SINKS[1:])):
            continue
        host = _host_fn(node)
        if host is None:
            continue
        for n in _hash_sink_slice(host, node):
            for sub in ast.walk(n):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _ORDER_METHODS
                    and not sub.args
                    and not _under_sorted(ctx, sub)
                    and not _allowed(ctx, sub.lineno)
                ):
                    f = emit(
                        sub,
                        f".{sub.func.attr}() order reaches the {base}() digest "
                        f"unsorted: dict order is arrival-order-bearing, so "
                        f"two processes observing the same world in different "
                        f"orders digest differently — wrap in sorted(...) or "
                        f"declare `# analysis: allow-determinism(<why>)`",
                    )
                    if f:
                        yield f

    for node in ctx.walk():
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            base = name.split(".")[-1]
            if (name in _FS_PRODUCERS or base in ("listdir", "scandir")) and not (
                _under_sorted(ctx, node) or _allowed(ctx, node.lineno)
            ):
                f = emit(
                    node,
                    f"{base}() enumeration order is filesystem-arbitrary — "
                    f"wrap in sorted(...) so restarts and replicas walk the "
                    f"same sequence, or declare "
                    f"`# analysis: allow-determinism(<why>)`",
                )
                if f:
                    yield f
            elif base in ("glob", "iglob") and name in ("glob.glob", "glob.iglob") and not (
                _under_sorted(ctx, node) or _allowed(ctx, node.lineno)
            ):
                f = emit(
                    node,
                    "glob() match order is filesystem-arbitrary — wrap in "
                    "sorted(...), or declare "
                    "`# analysis: allow-determinism(<why>)`",
                )
                if f:
                    yield f
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "popitem"
                and not node.args
                and not node.keywords
                and not _allowed(ctx, node.lineno)
            ):
                f = emit(
                    node,
                    "bare .popitem() pops by insertion-order recency — an "
                    "arrival-order artifact; use popitem(last=False) (FIFO, "
                    "the repo's eviction idiom) or an explicit key, or "
                    "declare `# analysis: allow-determinism(<why>)`",
                )
                if f:
                    yield f
            elif (
                base in ("tuple", "list")
                and node.args
                and _is_set_producer(node.args[0])
                and not _under_sorted(ctx, node)
                and not _allowed(ctx, node.lineno)
            ):
                f = emit(
                    node,
                    f"{base}() materializes a set's iteration order — "
                    f"PYTHONHASHSEED reorders it across processes; wrap in "
                    f"sorted(...), or declare "
                    f"`# analysis: allow-determinism(<why>)`",
                )
                if f:
                    yield f
        elif isinstance(node, ast.For):
            if (
                _is_set_producer(node.iter)
                and not _allowed(ctx, node.lineno)
            ):
                f = emit(
                    node,
                    "iterating a set literal/constructor — PYTHONHASHSEED "
                    "reorders it across processes; iterate sorted(...), or "
                    "declare `# analysis: allow-determinism(<why>)`",
                )
                if f:
                    yield f
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                if _is_set_producer(gen.iter) and not (
                    _under_sorted(ctx, node) or _allowed(ctx, node.lineno)
                ):
                    f = emit(
                        node,
                        "comprehension over a set producer — PYTHONHASHSEED "
                        "reorders it across processes; iterate sorted(...), "
                        "or declare `# analysis: allow-determinism(<why>)`",
                    )
                    if f:
                        yield f
