"""lock-discipline: guarded-field accesses outside ``with self._mu``.

The Go reference leans on ``go vet`` and the race detector for its
controller concurrency; the Python port's equivalent hazard is a method
touching ``Cluster.nodes`` (or a registry's ``values`` dict) without the
class's lock. The rule is self-calibrating per class:

1. A class participates iff some method assigns ``self.X =
   threading.Lock()`` / ``RLock()`` (any attribute name).
2. Its *guarded fields* are the ``self.*`` attributes MUTATED at least
   once inside a ``with self.<lock>`` block in a non-``__init__`` method
   (attribute assignment, ``self.x[k] = v`` subscript stores, or a
   mutating method call like ``.append``/``.pop``) — the code's own
   locking behavior defines the protected set, so read-only config
   fields (clients, clocks, bucket bounds set once in ``__init__``)
   never false-positive even when they happen to be read under the
   lock.
3. Every other access to a guarded field must be inside a ``with
   self.<lock>`` block, EXCEPT in private helpers (single leading
   underscore) whose intra-class call sites are all lock-held — the
   "caller holds the lock" convention, verified by a fixpoint over the
   call graph. Public methods must lock lexically: they are callable
   from anywhere.

``__init__``/``__new__`` are construction-time and exempt. Nested
functions reset the lock state (they run later, lock unknown) and
nested classes are skipped entirely (``self`` rebinds).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

from .engine import FileContext, dotted_name, rule
from .findings import SEV_ERROR, Finding

_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "Lock",
    "RLock",
}

_EXEMPT_METHODS = {"__init__", "__new__"}

_MUTATORS = {
    "append",
    "extend",
    "insert",
    "pop",
    "popitem",
    "remove",
    "discard",
    "add",
    "clear",
    "update",
    "setdefault",
    "sort",
    "reverse",
    "appendleft",
    "popleft",
}


def _self_field_root(node: ast.AST, locks: Set[str]) -> str:
    """Field name when an Attribute/Subscript chain roots at ``self.X``
    (X not a lock), else ''."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr if node.attr not in locks else ""
        node = node.value
    return ""


@dataclass
class _Access:
    field: str
    line: int
    locked: bool
    write: bool = False


@dataclass
class _MethodInfo:
    name: str
    accesses: List[_Access] = field(default_factory=list)
    # self-method calls: (callee, locked, line)
    calls: List[Tuple[str, bool, int]] = field(default_factory=list)


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute names assigned a Lock/RLock anywhere in the class."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if dotted_name(node.value.func) in _LOCK_CTORS:
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        out.add(t.attr)
    return out


def _is_lock_expr(expr: ast.AST, locks: Set[str]) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and expr.attr in locks
    )


def _scan_method(fn: ast.AST, locks: Set[str]) -> _MethodInfo:
    info = _MethodInfo(fn.name)

    call_funcs: Set[int] = set()  # self.<m>(...) func nodes — call edges, not field reads

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.ClassDef):
            return  # 'self' rebinds inside a nested class
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # a nested function runs later — lock state unknown, so
            # require it to lock (or be suppressed) on its own
            for child in ast.iter_child_nodes(node):
                visit(child, False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquires = any(_is_lock_expr(i.context_expr, locks) for i in node.items)
            for item in node.items:
                visit(item, locked)
            for stmt in node.body:
                visit(stmt, locked or acquires)
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr not in locks
            and id(node) not in call_funcs
        ):
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            info.accesses.append(_Access(node.attr, node.lineno, locked, write))
        # self.x[k] = v / del self.x[k]: a write to field x
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, (ast.Store, ast.Del)):
            root = _self_field_root(node, locks)
            if root:
                info.accesses.append(_Access(root, node.lineno, locked, True))
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
            ):
                info.calls.append((f.attr, locked, node.lineno))
                call_funcs.add(id(f))
            elif isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                # self.x.append(...) / self.x[k].update(...): mutation of x
                root = _self_field_root(f.value, locks)
                if root:
                    info.accesses.append(_Access(root, node.lineno, locked, True))
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    for stmt in fn.body:
        visit(stmt, False)
    return info


@rule(
    "lock-discipline",
    "guarded self.* fields must be accessed under the owning class's lock",
)
def check_lock_discipline(ctx: FileContext):
    for cls in ctx.walk():
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        methods: Dict[str, _MethodInfo] = {}
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[item.name] = _scan_method(item, locks)

        # "caller holds the lock" fixpoint for private helpers:
        # - assumed: ALL intra-class call sites lock-held -> accesses ok
        # - locked_ctx: AT LEAST ONE lock-held call site -> the helper's
        #   writes mark fields as guarded (a field mutated on a locked
        #   path is meant to be lock-protected, even when a second,
        #   unlocked path exists — that second path is the bug)
        callsites: Dict[str, List[Tuple[str, bool]]] = {}
        for caller, m in methods.items():
            for callee, locked, _line in m.calls:
                callsites.setdefault(callee, []).append((caller, locked))
        private = {
            n
            for n in methods
            if n.startswith("_") and not n.startswith("__") and callsites.get(n)
        }
        assumed = set(private)
        changed = True
        while changed:
            changed = False
            for n in list(assumed):
                for caller, locked in callsites.get(n, ()):
                    if not locked and caller not in assumed:
                        assumed.discard(n)
                        changed = True
                        break
        locked_ctx = set(assumed)
        changed = True
        while changed:
            changed = False
            for n in private - locked_ctx:
                if any(
                    locked or caller in locked_ctx
                    for caller, locked in callsites.get(n, ())
                ):
                    locked_ctx.add(n)
                    changed = True

        guarded: Set[str] = set()
        for name, m in methods.items():
            if name in _EXEMPT_METHODS:
                continue
            in_locked_ctx = name in locked_ctx
            for a in m.accesses:
                if a.write and (a.locked or in_locked_ctx):
                    guarded.add(a.field)
        if not guarded:
            continue

        lock_name = sorted(locks)[0]
        for name, m in methods.items():
            if name in _EXEMPT_METHODS or name in assumed:
                continue
            seen: Set[Tuple[str, int]] = set()
            for a in m.accesses:
                if a.locked or a.field not in guarded:
                    continue
                key = (a.field, a.line)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    rule="lock-discipline",
                    path=ctx.relpath,
                    line=a.line,
                    symbol=f"{cls.name}.{name}",
                    message=(
                        f"field '{a.field}' accessed without holding "
                        f"'self.{lock_name}' (guarded: used under the lock elsewhere "
                        f"in {cls.name})"
                    ),
                    severity=SEV_ERROR,
                )
