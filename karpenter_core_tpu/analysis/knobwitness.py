"""Runtime knob witness (ISSUE 20): observed env reads ⊆ static registry.

The static knob inventory (``configprov.repo_registry``) claims to be
authoritative. This module makes that claim falsifiable at runtime, the
lockwitness pattern: when ``KARPENTER_TPU_KNOB_WITNESS=1``, env access
is instrumented *before* the package (and jax) import, every
``KARPENTER_TPU_*`` name read during the test session is recorded, and
a session-teardown gate asserts each observed name is present in the
static inventory. A read the analyzer cannot see (an exec'd string, a
name built through a shape ``configprov`` doesn't resolve) fails tier-1
with instructions to extend the analyzer — never to weaken the gate.

Instrumentation detail: ``os._Environ`` inherits ``get`` and
``__contains__`` from ``Mapping`` (they route through ``__getitem__``),
so installing recording overrides on the *class* observes every
``os.environ.get`` / ``os.getenv`` / ``in`` probe while leaving
``__getitem__`` itself untouched — bulk snapshots (``dict(os.environ)``,
``os.environ.copy()``, subprocess spawning) do not pollute the observed
set with names the process never asked for individually.
"""

from __future__ import annotations

import os
import re
import threading
from typing import List, Optional, Set, Tuple

#: conftest reads this switch BEFORE install() — that probe is therefore
#: deliberately unrecorded, mirroring analysis/lockwitness.ENV_SWITCH
ENV_SWITCH = "KARPENTER_TPU_KNOB_WITNESS"

_PREFIX = "KARPENTER_TPU_"

_observed: Set[str] = set()
_mu = threading.Lock()
_installed = False


def _record(key: object) -> None:
    if isinstance(key, str) and key.startswith(_PREFIX):
        with _mu:
            _observed.add(key)


def install() -> None:
    """Instrument env access. Must run before the package (and jax)
    import so import-time reads are witnessed too."""
    global _installed
    if _installed:
        return
    env_cls = type(os.environ)

    def get(self, key, default=None):  # noqa: ANN001 — Mapping.get signature
        _record(key)
        try:
            return self[key]
        except KeyError:
            return default

    def contains(self, key):  # noqa: ANN001
        _record(key)
        try:
            self[key]
        except KeyError:
            return False
        return True

    env_cls.get = get
    env_cls.__contains__ = contains
    _installed = True


def installed() -> bool:
    return _installed


def observed_names() -> Set[str]:
    with _mu:
        return set(_observed)


def reset() -> None:
    with _mu:
        _observed.clear()


def verify_against_static(
    root: Optional[str] = None,
) -> Tuple[Set[str], List[str]]:
    """(observed, unexplained): every name read at runtime that the
    static knob inventory does not account for — by exact name or by a
    dynamic-knob pattern (f-string families like
    KARPENTER_TPU_SERVING_<NAME>_CAP)."""
    from .configprov import static_knob_names

    names, patterns = static_knob_names(root)
    names = set(names) | {ENV_SWITCH}
    observed = observed_names()
    unexplained = sorted(
        n
        for n in observed
        if n not in names and not any(p.match(n) for p in patterns)
    )
    return observed, unexplained
