"""tracer-safety: Python control flow on traced values inside jit/vmap.

A Python ``if``/``while``/``assert`` on a traced array inside a
``@jax.jit`` function raises ``TracerBoolConversionError`` at trace time
— but only on the first call with a new shape signature, so it can hide
until a production batch hits an untested size class. Worse, a branch on
a *concrete* value captured by closure silently bakes one side into the
compiled program. This rule finds both shapes statically:

- the *traced set* starts as the function's parameters minus
  ``static_argnames``/``static_argnums`` and grows through assignments
  (a value computed from a traced value is traced);
- ``.shape`` / ``.ndim`` / ``.dtype`` / ``.size`` / ``len()`` punch out
  of the traced set — shapes are static under jit, branching on them is
  the normal and correct pattern;
- ``if`` / ``while`` / ``assert`` tests and ``for`` iterables that
  reference a traced name are findings, as are nested ``lax.scan``/
  ``vmap`` body functions (their parameters are traced too).

Also checked: every ``static_argnames`` entry must name a real
parameter (a typo silently makes the argument traced), and a static
parameter must not have a mutable (unhashable) default — jit requires
hashable statics.

``static_argnums`` on METHODS (ISSUE 5 satellite): positional statics
count ``self`` as argument 0 when jit wraps the unbound function, the
classic off-by-one. Three checks: an index out of range (silently pins
nothing), index 0 on a method (pins ``self`` — unhashable instances
fail at dispatch, hashable ones silently specialize the compile cache
per instance), and off-by-one *evidence*: the pinned parameter is used
like an array (arithmetic/jnp ops) while the parameter one position to
the right is used only in static contexts (``if``/``while`` tests,
``len``/``range``) — exactly what a forgotten ``self`` offset looks
like. Prefer ``static_argnames``: names cannot shift.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from .engine import FileContext, jit_decoration, rule
from .findings import SEV_ERROR, Finding

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "itemsize"}
_STATIC_FNS = {"len", "isinstance", "type", "hasattr", "getattr"}


def _params(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _refs_traced(expr: ast.AST, traced: Set[str]) -> str:
    """Name of a traced value the expression depends on, or ''. Shape/
    dtype accesses and len() are static under jit and stop the search."""
    if isinstance(expr, ast.Attribute):
        if expr.attr in _SHAPE_ATTRS:
            return ""
        return _refs_traced(expr.value, traced)
    if isinstance(expr, ast.Call):
        fname = expr.func.id if isinstance(expr.func, ast.Name) else ""
        if fname in _STATIC_FNS:
            return ""
        hit = ""
        for child in list(expr.args) + [kw.value for kw in expr.keywords]:
            hit = _refs_traced(child, traced)
            if hit:
                return hit
        if not isinstance(expr.func, ast.Name):
            return _refs_traced(expr.func, traced)
        return ""
    if isinstance(expr, ast.Name):
        return expr.id if expr.id in traced else ""
    for child in ast.iter_child_nodes(expr):
        hit = _refs_traced(child, traced)
        if hit:
            return hit
    return ""


def _scan_jit_body(
    ctx: FileContext, fn: ast.AST, symbol: str, traced: Set[str]
) -> Iterable[Finding]:
    def finding(line: int, kind: str, name: str) -> Finding:
        return Finding(
            rule="tracer-safety",
            path=ctx.relpath,
            line=line,
            symbol=symbol,
            message=(
                f"Python {kind} on traced value '{name}' inside a jit/vmap "
                f"function — use jnp.where/lax.cond or mark the argument static"
            ),
            severity=SEV_ERROR,
        )

    def visit(body: Iterable[ast.AST], traced: Set[str]) -> Iterable[Finding]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested function (scan/vmap body): its params are traced
                inner = set(traced) | set(_params(stmt))
                yield from visit(stmt.body, inner)
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = stmt.value
                targets = []
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        targets.extend(_target_names(t))
                else:
                    targets.extend(_target_names(stmt.target))
                if value is not None and _refs_traced(value, traced):
                    traced.update(targets)
                else:
                    for t in targets:
                        traced.discard(t)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                name = _refs_traced(stmt.test, traced)
                if name:
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    yield finding(stmt.lineno, f"'{kind}'", name)
                yield from visit(stmt.body, traced)
                yield from visit(stmt.orelse, traced)
                continue
            if isinstance(stmt, ast.Assert):
                name = _refs_traced(stmt.test, traced)
                if name:
                    yield finding(stmt.lineno, "'assert'", name)
                continue
            if isinstance(stmt, ast.For):
                name = _refs_traced(stmt.iter, traced)
                if name:
                    yield finding(stmt.lineno, "'for' iteration", name)
                yield from visit(stmt.body, traced)
                yield from visit(stmt.orelse, traced)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from visit(stmt.body, traced)
                continue
            if isinstance(stmt, ast.Try):
                yield from visit(stmt.body, traced)
                for h in stmt.handlers:
                    yield from visit(h.body, traced)
                yield from visit(stmt.orelse, traced)
                yield from visit(stmt.finalbody, traced)
                continue

    yield from visit(fn.body, set(traced))


def _target_names(t: ast.AST) -> List[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    return []


_MUTABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)


def _name_uses(fn: ast.AST, param: str) -> List[Tuple[ast.Name, ast.AST]]:
    """(name node, parent) pairs for every Load of ``param`` in ``fn``."""
    parents: dict = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return [
        (n, parents.get(n))
        for n in ast.walk(fn)
        if isinstance(n, ast.Name) and n.id == param and isinstance(n.ctx, ast.Load)
    ]


def _used_traced_like(fn: ast.AST, param: str) -> bool:
    """The parameter flows through array-shaped operations."""
    for n, parent in _name_uses(fn, param):
        if isinstance(parent, ast.BinOp):
            return True
        if isinstance(parent, ast.Call):
            f = parent.func
            dn = ""
            node = f
            parts = []
            while isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
            if isinstance(node, ast.Name):
                parts.append(node.id)
                dn = ".".join(reversed(parts))
            if dn.split(".")[0] in ("jnp", "lax", "jax", "np"):
                return True
        if isinstance(parent, ast.Subscript) and parent.value is n:
            return True
    return False


_STATIC_PARENT_FNS = {"len", "range", "isinstance", "type", "hasattr"}


def _used_static_only(fn: ast.AST, param: str) -> bool:
    """Every use of the parameter is hashable/static-shaped: an
    ``if``/``while`` test, a ``len``/``range`` argument, a subscript
    index, or a comparison."""
    uses = _name_uses(fn, param)
    if not uses:
        return False
    tests = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)):
            tests.update(id(x) for x in ast.walk(node.test))
    for n, parent in uses:
        if id(n) in tests:
            continue
        if (
            isinstance(parent, ast.Call)
            and isinstance(parent.func, ast.Name)
            and parent.func.id in _STATIC_PARENT_FNS
        ):
            continue
        if isinstance(parent, ast.Subscript) and parent.slice is n:
            continue
        if isinstance(parent, ast.Compare):
            continue
        return False
    return True


@rule(
    "tracer-safety",
    "no Python control flow on traced values in jit/vmap functions; statics must be real, hashable params",
)
def check_tracer_safety(ctx: FileContext):
    method_ids: Set[int] = set()
    for cls in ctx.walk():
        if isinstance(cls, ast.ClassDef):
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    method_ids.add(id(item))
    for node in ctx.walk():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info = jit_decoration(node)
        if info is None:
            continue
        params = _params(node)
        static: Set[str] = set(info["static_names"])
        for i in info["static_nums"]:
            if 0 <= i < len(params):
                static.add(params[i])
        symbol = node.name
        for sname in info["static_names"]:
            if sname not in params:
                yield Finding(
                    rule="tracer-safety",
                    path=ctx.relpath,
                    line=node.lineno,
                    symbol=symbol,
                    message=(
                        f"static_argnames entry '{sname}' is not a parameter of "
                        f"'{node.name}' — the argument it meant to pin stays traced"
                    ),
                    severity=SEV_ERROR,
                )
        # static_argnums checks (ISSUE 5): range, pinned self, and the
        # bound-method off-by-one (self occupies position 0)
        is_method = id(node) in method_ids and params[:1] in (["self"], ["cls"])
        for i in info["static_nums"]:
            if i >= len(params) or i < -len(params):
                yield Finding(
                    rule="tracer-safety",
                    path=ctx.relpath,
                    line=node.lineno,
                    symbol=symbol,
                    message=(
                        f"static_argnums entry {i} is out of range for "
                        f"'{node.name}' ({len(params)} parameters) — it pins "
                        f"nothing and the intended argument stays traced"
                    ),
                    severity=SEV_ERROR,
                )
                continue
            if is_method and params[i % len(params)] in ("self", "cls"):
                yield Finding(
                    rule="tracer-safety",
                    path=ctx.relpath,
                    line=node.lineno,
                    symbol=symbol,
                    message=(
                        f"static_argnums={i} on method '{node.name}' pins "
                        f"'{params[i % len(params)]}' — positional statics "
                        f"count self as argument 0; use static_argnames"
                    ),
                    severity=SEV_ERROR,
                )
                continue
            if is_method and 0 < i < len(params) - 1:
                pinned, shifted = params[i], params[i + 1]
                if _used_traced_like(node, pinned) and _used_static_only(
                    node, shifted
                ):
                    yield Finding(
                        rule="tracer-safety",
                        path=ctx.relpath,
                        line=node.lineno,
                        symbol=symbol,
                        message=(
                            f"static_argnums={i} on method '{node.name}' pins "
                            f"'{pinned}' (used like an array) while "
                            f"'{shifted}' is used only statically — likely a "
                            f"self off-by-one; use static_argnames"
                        ),
                        severity=SEV_ERROR,
                    )
        # mutable default on a static param — unhashable at dispatch time
        a = node.args
        pos = a.posonlyargs + a.args
        for p, d in zip(pos[len(pos) - len(a.defaults) :], a.defaults):
            if p.arg in static and isinstance(d, _MUTABLE_DEFAULTS):
                yield Finding(
                    rule="tracer-safety",
                    path=ctx.relpath,
                    line=d.lineno,
                    symbol=symbol,
                    message=(
                        f"static parameter '{p.arg}' has an unhashable default — "
                        f"jit requires hashable static arguments"
                    ),
                    severity=SEV_ERROR,
                )
        traced = {p for p in params if p not in static and p != "self"}
        yield from _scan_jit_body(ctx, node, symbol, traced)
