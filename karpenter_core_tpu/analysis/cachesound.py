"""cachesound: prove every cross-solve memo key witnesses its read-set.

PR 4 rests the incremental solver on one invariant — a warm solve is
plan-identical to a cold solve because every cache is content-addressed
by the exact inputs of a deterministic computation. Until this rule
family, that invariant was defended only by test coverage (the bench-7
oracle, the invalidation matrix). These project rules turn it into a
static gate, the same way salsa/Adapton-style incremental systems make
key/read-set discipline structural:

- **cache-key** (key-completeness): for every memo site on a registered
  cross-solve container (the ``LRU`` caches of ``solver/incremental.py``,
  ``runtime_caches``/``sig_rows`` on catalog entries, ``_CATALOG_CACHE``,
  the podcache intern maps and pod memo, the cross-engine intersects
  memo, and the ``seeds_get``/``seeds_put`` accessor pair), compute the
  read-set of the cached computation by AST dataflow (free variables,
  attribute/subscript paths on solver/cluster/catalog state, values
  flowing through one level of same-project calls) and report any input
  not witnessed by the key expressions, a declared generation guard, or
  a scoped ``# analysis: allow-cache-key(<input>, ...) — reason`` marker.
  The get-side and put-side key expressions must also witness the same
  input roots (a key edited at one end of a split site is exactly the
  kind of bug that corrupts plans under churn).

- **cache-invalidation** (invalidation-completeness): every mutator of
  ``state/cluster.py`` informer state that writes fields the solver's
  caches can observe (derived from the cluster API the consumer modules
  actually call) must bump ``Cluster.generation()`` — directly, through
  a bump helper, or through the "all callers bump" fixpoint for private
  helpers. Symmetrically, any provider class maintaining a
  ``catalog_generation()`` must bump (or reset) it in every method that
  writes catalog-backing fields (the fields ``get_instance_types``
  reads).

- **cache-determinism** (key-determinism): process-unstable material in
  key/digest construction — builtin ``hash()`` anywhere in the cache
  modules (PYTHONHASHSEED), ``id()`` in key material (recycled
  addresses), iteration order of sets materialized without ``sorted``,
  ``repr`` of objects, float-through-``str`` feeding digests, and
  traced/device values flowing into a key (a tracer leak AND a soundness
  bug).

- **cache-persist** (persisted-key re-anchoring, ISSUE 13): the
  warm-state snapshot/restore seam (``solver/warmstore.py``) must
  re-anchor restored planes against the LIVE world — never install a
  persisted generation counter (another process's ordinal), never drop
  the tenant scope while rebinding persisted keys, and never trust a
  payload whose schema id / key-layout contract hash it has not
  verified.

The analysis is necessarily an approximation; its residual assumptions
are (a) one level of call inlining — deeper callees are modeled as
reading their arguments, and (b) ALL_CAPS module constants are process
config, not per-tick inputs. Both are documented in RULES.md; the
mutation-kill meta-test (tests/test_cachesound.py) demonstrates the
approximation still kills the realistic bug classes: a dropped key
component per cache, a deleted generation bump, a salted fingerprint.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import FileContext, ProjectContext, dotted_name, project_rule
from .findings import SEV_ERROR, Finding, scoped_marker_args

Path = Tuple[str, ...]

_WILD = "*"

# builtins whose calls read only their arguments
_PURE_BUILTINS = {
    "len", "range", "enumerate", "zip", "sorted", "reversed", "min", "max",
    "sum", "abs", "round", "tuple", "list", "dict", "set", "frozenset",
    "int", "float", "bool", "str", "bytes", "id", "hash", "repr", "iter",
    "next", "map", "filter", "any", "all", "isinstance", "issubclass",
    "callable", "print", "format", "vars", "type", "hasattr", "divmod",
}

# module roots that never carry per-tick solve inputs
_BENIGN_ROOTS = {
    "np", "jnp", "jax", "math", "os", "hashlib", "struct", "threading",
    "itertools", "functools", "collections", "time", "logging", "re",
}

_INLINE_DEPTH = 2
_INLINE_STMT_CAP = 400


def _is_const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def render(path: Path) -> str:
    parts = [p for p in path if p != _WILD]
    return ".".join(parts) if parts else path[0]


def parse_marker_path(text: str) -> Path:
    parts = [p for p in re.split(r"[.\[\]]+", text) if p]
    return tuple(parts)


def paths_match(a: Path, b: Path) -> bool:
    """True when one path is a (wildcard-tolerant) prefix of the other."""
    for x, y in zip(a, b):
        if x != y and x != _WILD and y != _WILD:
            return False
    return True


def rootkey(path: Path) -> Path:
    """Comparison granularity for roots: ``self``-rooted paths compare on
    the first attribute (``self._a`` vs ``self._b`` are distinct roots)."""
    if path and path[0] == "self":
        return path[:2]
    return path[:1]


# ---------------------------------------------------------------------------
# project symbol index


@dataclass
class FnInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    ctx: FileContext
    cls: Optional[str]
    symbol: str

    @property
    def name(self) -> str:
        return self.node.name


@dataclass
class ClassInfo:
    node: ast.ClassDef
    ctx: FileContext
    methods: Dict[str, FnInfo] = field(default_factory=dict)
    properties: Set[str] = field(default_factory=set)


@dataclass
class ModuleInfo:
    ctx: FileContext
    functions: Dict[str, FnInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    # import alias -> repo relpath (project modules) or None (external)
    imports: Dict[str, Optional[str]] = field(default_factory=dict)
    # name imported via `from .mod import name` -> (relpath, name)
    from_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    globals_caps: Set[str] = field(default_factory=set)  # ALL_CAPS constants


def _index_module(ctx: FileContext) -> ModuleInfo:
    mi = ModuleInfo(ctx)
    for node in ctx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mi.functions[node.name] = FnInfo(node, ctx, None, node.name)
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo(node, ctx)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # a property setter/deleter must not shadow the
                    # getter (same def name): reads resolve to the getter
                    accessor = any(
                        dotted_name(d).endswith((".setter", ".deleter"))
                        for d in item.decorator_list
                    )
                    if not (accessor and item.name in ci.methods):
                        ci.methods[item.name] = FnInfo(
                            item, ctx, node.name, f"{node.name}.{item.name}"
                        )
                    for dec in item.decorator_list:
                        if dotted_name(dec) in ("property", "cached_property"):
                            ci.properties.add(item.name)
            mi.classes[node.name] = ci
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id.upper() == t.id:
                    mi.globals_caps.add(t.id)
    for node in ctx.walk():
        if isinstance(node, ast.Import):
            for a in node.names:
                mi.imports[a.asname or a.name.split(".")[0]] = None
        elif isinstance(node, ast.ImportFrom):
            pkg = ctx.relpath.split("/")[:-1]
            if node.level > 1:
                pkg = pkg[: len(pkg) - (node.level - 1)]
            for a in node.names:
                local = a.asname or a.name
                if not node.level:
                    mi.imports[local] = None
                elif node.module is None:
                    # `from . import merge [as merge_mod]`: submodule alias
                    mi.imports[local] = "/".join(pkg + [a.name]) + ".py"
                else:
                    rel = "/".join(pkg + node.module.split(".")) + ".py"
                    mi.from_imports[local] = (rel, a.name)
    return mi


# ---------------------------------------------------------------------------
# registered cross-solve containers


@dataclass(frozen=True)
class ContainerSpec:
    name: str  # human cache name (finding messages)
    owner_scoped: bool = False  # owner object is a content address


class Registry:
    def __init__(self) -> None:
        self.attrs: Dict[str, ContainerSpec] = {}
        self.globals: Dict[str, ContainerSpec] = {}

    def for_receiver(self, path: Optional[Path]) -> Optional[ContainerSpec]:
        if not path:
            return None
        last = path[-1]
        spec = self.attrs.get(last)
        if spec is not None and len(path) > 1:
            return spec
        if len(path) == 1:
            return self.globals.get(path[0])
        return None


def _build_registry(files: Sequence[FileContext]) -> Registry:
    reg = Registry()
    # fixed containers: catalog-entry scoped rows, the catalog cache, the
    # podcache intern maps, the cross-engine intersects memo
    reg.attrs["runtime_caches"] = ContainerSpec("runtime_caches", owner_scoped=True)
    reg.attrs["sig_rows"] = ContainerSpec("sig_rows", owner_scoped=True)
    reg.attrs["_intersects_cache"] = ContainerSpec("intersects")
    for g in ("_CATALOG_CACHE", "_REQ_INTERN", "_SIG_INTERN"):
        reg.globals[g] = ContainerSpec(g.strip("_").lower())
    # discovered: every `self.X = LRU("name")` is a cross-solve cache
    for f in files:
        for node in f.walk():
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and dotted_name(node.value.func) in ("LRU", "incremental.LRU")
            ):
                cname = None
                if node.value.args:
                    cname = _is_const_str(node.value.args[0])
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        reg.attrs[t.attr] = ContainerSpec(cname or t.attr)
    return reg


# skip cache-plumbing scopes: the containers' own implementation
# (SkeletonPlane is the fleet content plane's accessor pair — its
# call sites in solver._pack_and_finalize are the analyzed sites,
# exactly like WarmState's seeds_get/seeds_put)
_PLUMBING_CLASSES = {"LRU", "CacheStats", "WarmState", "SkeletonPlane"}
_PLUMBING_FNS = {"warm_state_for", "reset", "cache_cap", "enabled"}

# tenant-scoped caches (ISSUE 9): their validity guards are PER-OBJECT
# generation counters (a cluster's informer generation, a provider's
# catalog generation), so the key must also witness WHICH tenant's
# object the guard belongs to — equal counter values from two tenants'
# objects witness nothing about each other, and a key without the
# tenant component would serve one tenant's entries to another.
_TENANT_SCOPED_SPECS = {"seeds", "fleetenv"}
_TENANT_WITNESS_SEGMENTS = {"_tenant_scope", "tenant_id", "tenant"}


def _own_nodes(fn: ast.AST):
    """Walk a function's own statements/expressions, NOT descending into
    nested functions/lambdas/classes (their locals are a separate scope
    and their bodies run at call time)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# per-function dataflow scope


class Scope:
    """Function-local def-use environment with path substitution."""

    def __init__(self, analyzer: "Analyzer", fn: FnInfo):
        self.analyzer = analyzer
        self.fn = fn
        node = fn.node
        a = node.args
        self.params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            self.params.append(a.vararg.arg)
        if a.kwarg:
            self.params.append(a.kwarg.arg)
        self.assigns: Dict[str, List[ast.AST]] = {}
        # values flowing INTO a container (x[k] = v, x.append(v)): part
        # of the container's dataflow but NOT a rebinding of the name —
        # kept apart so receiver alias-chasing stays sound
        self.elem_assigns: Dict[str, List[ast.AST]] = {}
        # (self, X) attribute assignments within this function
        self.attr_assigns: Dict[Tuple[str, str], List[ast.AST]] = {}
        # name -> (iterable expr, extra wildcard) loop/with bindings
        self.loop_binds: Dict[str, Tuple[ast.AST, bool]] = {}
        # names provably bound to pure indices (enumerate counters):
        # free-path-less by construction
        self.void: Set[str] = set()
        self._collect(node)

    def _bind_target(self, t: ast.AST, value: Optional[ast.AST]) -> None:
        if isinstance(t, ast.Name):
            if value is not None:
                self.assigns.setdefault(t.id, []).append(value)
        elif isinstance(t, ast.Subscript):
            # keys[i] = v: v flows into the container
            base = t.value
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            if isinstance(base, ast.Name) and value is not None:
                self.elem_assigns.setdefault(base.id, []).append(value)
        elif isinstance(t, (ast.Tuple, ast.List)) and value is not None:
            if isinstance(value, ast.Tuple) and len(value.elts) == len(t.elts):
                for sub, v in zip(t.elts, value.elts):
                    self._bind_target(sub, v)
            elif (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "zip"
                and len(value.args) == len(t.elts)
            ):
                for sub, v in zip(t.elts, value.args):
                    if isinstance(sub, ast.Name):
                        self.loop_binds[sub.id] = (v, True)
                    else:
                        self._bind_target(sub, v)
            elif (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "enumerate"
                and value.args
                and len(t.elts) == 2
            ):
                # index binds to nothing; element to the container
                if isinstance(t.elts[0], ast.Name):
                    self.void.add(t.elts[0].id)
                if isinstance(t.elts[1], ast.Name):
                    self.loop_binds[t.elts[1].id] = (value.args[0], True)
                else:
                    self._bind_target(t.elts[1], value.args[0])
            else:
                for sub in t.elts:
                    if isinstance(sub, ast.Name):
                        self.loop_binds[sub.id] = (value, True)
        elif isinstance(t, ast.Attribute):
            if (
                isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and value is not None
            ):
                self.attr_assigns.setdefault(("self", t.attr), []).append(value)

    def _collect(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested scopes don't rebind ours
            if isinstance(child, ast.ClassDef):
                continue
            if isinstance(child, ast.Assign):
                for t in child.targets:
                    self._bind_target(t, child.value)
            elif isinstance(child, ast.AnnAssign) and child.value is not None:
                self._bind_target(child.target, child.value)
            elif isinstance(child, ast.AugAssign):
                self._bind_target(child.target, child.value)
            elif isinstance(child, ast.For):
                self._bind_loop(child.target, child.iter)
            elif isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    if item.optional_vars is not None:
                        self._bind_target(item.optional_vars, item.context_expr)
            elif isinstance(child, ast.Expr) and isinstance(child.value, ast.Call):
                call = child.value
                f = call.func
                if isinstance(f, ast.Attribute) and f.attr in (
                    "append",
                    "add",
                    "extend",
                    "appendleft",
                    "insert",
                ):
                    if isinstance(f.value, ast.Name):
                        for arg in call.args:
                            self.elem_assigns.setdefault(f.value.id, []).append(arg)
            if isinstance(child, ast.NamedExpr):
                self._bind_target(child.target, child.value)
            self._collect(child)

    def _bind_loop(self, target: ast.AST, it: ast.AST) -> None:
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id == "range"
        ):
            for t in ast.walk(target):
                if isinstance(t, ast.Name):
                    self.void.add(t.id)
            return
        if isinstance(target, ast.Name):
            self.loop_binds[target.id] = (it, True)
        else:
            self._bind_target(target, it)


# ---------------------------------------------------------------------------
# free-path extraction


class Analyzer:
    """Shared cross-file machinery for the three cachesound rules."""

    def __init__(self, pctx: ProjectContext):
        self.pctx = pctx
        self.modules: Dict[str, ModuleInfo] = {}
        files = pctx.matching(pctx.config.cache_modules)
        extra = pctx.matching(
            tuple(pctx.config.state_modules)
            + tuple(pctx.config.provider_modules)
            + tuple(pctx.config.cluster_consumer_modules)
        )
        seen = set()
        self.cache_files: List[FileContext] = []
        for f in files:
            if f.relpath not in seen:
                seen.add(f.relpath)
                self.cache_files.append(f)
                self.modules[f.relpath] = _index_module(f)
        for f in extra:
            if f.relpath not in seen:
                seen.add(f.relpath)
                self.modules[f.relpath] = _index_module(f)
        self.registry = _build_registry(self.cache_files)
        self._scopes: Dict[int, Scope] = {}
        self._free_memo: Dict[tuple, Tuple[Set[Path], Set[Path]]] = {}
        # key mode: witness extraction UNDER-approximates — a subscript
        # index's own provenance (``groups[gi]`` with gi from a cache-
        # state-derived list) selects an element but is not key content;
        # folding it in would let cache state witness keys, masking
        # dropped components. Reads keep the index paths (over-approx is
        # the safe direction for the read-set).
        self._key_mode = False
        # cycle-guard bookkeeping: a memo entry records the guard keys
        # that fired while computing it; the entry is valid exactly when
        # those guards would fire again (fired ⊆ current visiting), so
        # cyclic chains stay correct without poisoning the memo
        self._fired_stack: List[set] = []
        self._name_memo: Dict[tuple, tuple] = {}
        # comprehension overlays rebind names temporarily: memo entries
        # carry the active overlay stack (comp node ids) so a resolution
        # under overlay bindings is cached for — and only served back to
        # — the same comprehension context
        self._overlay_token: tuple = ()
        self._callee_memo: Dict[tuple, tuple] = {}
        self._fn_size: Dict[int, int] = {}

    def scope_for(self, fn: FnInfo) -> Scope:
        s = self._scopes.get(id(fn.node))
        if s is None:
            s = Scope(self, fn)
            self._scopes[id(fn.node)] = s
        return s

    def module_of(self, fn: FnInfo) -> ModuleInfo:
        return self.modules[fn.ctx.relpath]

    # -- call resolution -------------------------------------------------

    def resolve_call(self, call: ast.Call, fn: FnInfo) -> Optional[FnInfo]:
        f = call.func
        mi = self.module_of(fn)
        if isinstance(f, ast.Name):
            if f.id in mi.functions:
                return mi.functions[f.id]
            tgt = mi.from_imports.get(f.id)
            if tgt is not None:
                tmi = self.modules.get(tgt[0])
                if tmi is not None:
                    return tmi.functions.get(tgt[1])
            return None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                if f.value.id in ("self", "cls") and fn.cls is not None:
                    ci = mi.classes.get(fn.cls)
                    if ci is not None:
                        return ci.methods.get(f.attr)
                mod_rel = mi.imports.get(f.value.id)
                if mod_rel is not None:
                    tmi = self.modules.get(mod_rel)
                    if tmi is not None:
                        return tmi.functions.get(f.attr)
        return None

    def resolve_property(self, base: str, attr: str, fn: FnInfo) -> Optional[FnInfo]:
        if base != "self" or fn.cls is None:
            return None
        ci = self.module_of(fn).classes.get(fn.cls)
        if ci is not None and attr in ci.properties:
            return ci.methods.get(attr)
        return None

    # -- free paths ------------------------------------------------------
    #
    # Two-set model: ``objs`` are paths that still denote the object a
    # name is bound to (attribute/subscript suffixes remain meaningful:
    # ``m -> merged[*]`` means ``m["enc"] -> merged[*].enc``). ``derived``
    # are reads that merely fed the value's construction (a constructor
    # argument, an arithmetic operand) — suffixing them would invent
    # paths that don't exist (``b = Bucket(solver); b.k`` is NOT
    # ``solver.k``).

    def free(self, expr: ast.AST, fn: FnInfo, depth: int = 0) -> Set[Path]:
        o, d = self._split(expr, fn, depth, frozenset())
        return o | d

    def _free(
        self, expr: ast.AST, fn: FnInfo, depth: int, visiting: frozenset
    ) -> Set[Path]:
        o, d = self._split(expr, fn, depth, visiting)
        return o | d

    def free_key(self, expr: ast.AST, fn: FnInfo) -> Set[Path]:
        """Witness-side extraction (key mode: no index provenance)."""
        saved = self._key_mode
        self._key_mode = True
        try:
            return self.free(expr, fn)
        finally:
            self._key_mode = saved

    def _split(
        self, expr: ast.AST, fn: FnInfo, depth: int, visiting: frozenset
    ) -> Tuple[Set[Path], Set[Path]]:
        key = (id(expr), id(fn.node), depth, self._key_mode, self._overlay_token)
        hit = self._memo_get(self._free_memo, key, visiting)
        if hit is not None:
            return hit
        self._fired_stack.append(set())
        try:
            out = self._split_uncached(expr, fn, depth, visiting)
        finally:
            fired = self._fired_stack.pop()
        self._memo_put(self._free_memo, key, out, fired)
        return out

    def _memo_get(self, memo: dict, key: tuple, visiting: frozenset):
        hit = memo.get(key)
        if hit is None:
            return None
        out, fired = hit
        if not fired <= visiting:
            return None  # different cycle context: recompute
        if fired and self._fired_stack:
            self._fired_stack[-1] |= fired
        return out

    def _memo_put(self, memo: dict, key: tuple, out, fired: set) -> None:
        memo[key] = (out, frozenset(fired))
        if fired and self._fired_stack:
            self._fired_stack[-1] |= fired

    def _guard_fired(self, vkey) -> None:
        if self._fired_stack:
            self._fired_stack[-1].add(vkey)

    def _name_split(
        self, name: str, fn: FnInfo, depth: int, visiting: frozenset
    ) -> Tuple[Set[Path], Set[Path]]:
        mkey = (id(fn.node), name, depth, self._key_mode, self._overlay_token)
        hit = self._memo_get(self._name_memo, mkey, visiting)
        if hit is not None:
            return hit
        self._fired_stack.append(set())
        try:
            out = self._name_split_uncached(name, fn, depth, visiting)
        finally:
            fired = self._fired_stack.pop()
        fired.discard((id(fn.node), name))  # own cycle: fixpoint reached
        self._memo_put(self._name_memo, mkey, out, fired)
        return out

    def _name_split_uncached(
        self, name: str, fn: FnInfo, depth: int, visiting: frozenset
    ) -> Tuple[Set[Path], Set[Path]]:
        none: Set[Path] = set()
        if name in _PURE_BUILTINS or name == "cls":
            return none, none
        if name == "self":
            return {("self",)}, none
        mi = self.module_of(fn)
        if name in mi.imports or name in ("tracer",):
            return none, none
        scope = self.scope_for(fn)
        if name in scope.void:
            return none, none
        vkey = (id(fn.node), name)
        if vkey in visiting:
            self._guard_fired(vkey)
            return none, none
        visiting = visiting | {vkey}
        objs: Set[Path] = set()
        derived: Set[Path] = set()
        resolved = False
        if name in scope.params:
            # the identity path dominates: element-writes into a param
            # (m["zone"] = ...) don't dissolve the object into the
            # written values
            return {(name,)}, none
        if name in scope.loop_binds:
            it, wild = scope.loop_binds[name]
            o, d = self._split(it, fn, depth, visiting)
            objs |= {p + ((_WILD,) if wild else ()) for p in o}
            derived |= d
            resolved = True
        if name in scope.assigns:
            for v in scope.assigns[name]:
                o, d = self._split(v, fn, depth, visiting)
                objs |= o
                derived |= d
            resolved = True
        if name in scope.elem_assigns:
            for v in scope.elem_assigns[name]:
                o, d = self._split(v, fn, depth, visiting)
                objs |= o
                derived |= d
            resolved = True
        if resolved:
            return objs, derived
        if name in mi.functions or name in mi.classes or name in mi.from_imports:
            return none, none
        if name in mi.globals_caps:
            return none, none  # process config, stable for the process
        return {(name,)}, none

    def _chain(self, expr: ast.AST) -> Optional[Tuple[str, Path]]:
        """(base name, suffix path) for Name/Attribute/const-Subscript
        chains, else None."""
        full = self._chain_full(expr)
        return None if full is None else (full[0], full[1])

    def _chain_full(
        self, expr: ast.AST
    ) -> Optional[Tuple[str, Path, List[ast.AST]]]:
        """Like ``_chain`` plus the non-constant index expressions met
        along the spine (their reads are selection provenance)."""
        parts: List[str] = []
        indices: List[ast.AST] = []
        node = expr
        while True:
            if isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
            elif isinstance(node, ast.Subscript):
                c = node.slice
                if isinstance(c, ast.Constant) and isinstance(c.value, (str, int)):
                    parts.append(str(c.value))
                else:
                    parts.append(_WILD)
                    indices.append(c)
                node = node.value
            elif isinstance(node, ast.Name):
                return node.id, tuple(reversed(parts)), indices
            else:
                return None

    # constructors that hand back (a view of) their first argument:
    # suffixes on the result still address the argument's content
    _COPY_CALLS = {"dict", "list", "tuple", "sorted", "reversed"}

    def _split_uncached(
        self, expr: ast.AST, fn: FnInfo, depth: int, visiting: frozenset
    ) -> Tuple[Set[Path], Set[Path]]:
        none: Set[Path] = set()
        if expr is None or isinstance(expr, ast.Constant):
            return none, none
        if isinstance(expr, ast.Name):
            return self._name_split(expr.id, fn, depth, visiting)
        if isinstance(expr, ast.Starred):
            return self._split(expr.value, fn, depth, visiting)
        if isinstance(expr, (ast.Attribute, ast.Subscript)):
            chain = self._chain_full(expr)
            if chain is not None:
                base, suffix, indices = chain
                # property inlining: self.<prop> resolves to its body
                if base == "self" and suffix:
                    prop = self.resolve_property(base, suffix[0], fn)
                    if prop is not None and depth < _INLINE_DEPTH:
                        body = self._callee_free(prop, depth + 1, visiting)
                        mapped = self._map_paths(
                            body, prop, [], {}, ("self",), fn, depth, visiting
                        )
                        return none, ({p + suffix[1:] for p in mapped} or mapped)
                    # self-attr assigned in this function: substitute
                    scope = self.scope_for(fn)
                    akey = ("self", suffix[0])
                    if akey in scope.attr_assigns:
                        vkey = (id(fn.node), "attr:" + suffix[0])
                        if vkey in visiting:
                            self._guard_fired(vkey)
                        if vkey not in visiting:
                            v2 = visiting | {vkey}
                            objs: Set[Path] = set()
                            derived: Set[Path] = set()
                            for v in scope.attr_assigns[akey]:
                                o, d = self._split(v, fn, depth, v2)
                                objs |= {p + suffix[1:] for p in o}
                                derived |= d
                            if objs or derived:
                                return objs, derived
                o, d = self._name_split(base, fn, depth, visiting)
                objs = {bp + suffix for bp in o}
                derived = set(d)
                # non-const subscript indices contribute their own reads
                # (suppressed in key mode — selection, not key content)
                if not self._key_mode:
                    for idx in indices:
                        derived |= self._free(idx, fn, depth, visiting)
                return objs, derived
            # complex base (call result etc.): suffixes don't survive
            derived = set()
            for child in ast.iter_child_nodes(expr):
                derived |= self._free(child, fn, depth, visiting)
            return none, derived
        if isinstance(expr, ast.Call):
            return self._call_split(expr, fn, depth, visiting)
        if isinstance(expr, (ast.Tuple, ast.List)):
            objs, derived = set(), set()
            for e in expr.elts:
                o, d = self._split(e, fn, depth, visiting)
                objs |= o
                derived |= d
            return objs, derived
        if isinstance(expr, ast.BoolOp):
            objs, derived = set(), set()
            for e in expr.values:
                o, d = self._split(e, fn, depth, visiting)
                objs |= o
                derived |= d
            return objs, derived
        if isinstance(expr, ast.IfExp):
            o1, d1 = self._split(expr.body, fn, depth, visiting)
            o2, d2 = self._split(expr.orelse, fn, depth, visiting)
            d = d1 | d2
            if not self._key_mode:  # the test selects a branch, it is
                d |= self._free(expr.test, fn, depth, visiting)  # not key content
            return o1 | o2, d
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp)):
            return self._comp_split(expr, fn, depth, visiting)
        if isinstance(expr, (ast.SetComp, ast.DictComp)):
            o, d = self._comp_split(expr, fn, depth, visiting)
            return none, o | d  # unordered containers: nothing addressable
        if isinstance(expr, ast.Lambda):
            return none, none
        derived = set()
        for child in ast.iter_child_nodes(expr):
            derived |= self._free(child, fn, depth, visiting)
        return none, derived

    def _comp_split(
        self, expr: ast.AST, fn: FnInfo, depth: int, visiting: frozenset
    ) -> Tuple[Set[Path], Set[Path]]:
        # overlay the generator bindings on a shallow scope copy
        scope = self.scope_for(fn)
        saved_loop = dict(scope.loop_binds)
        saved_assigns = {k: list(v) for k, v in scope.assigns.items()}
        saved_elems = {k: list(v) for k, v in scope.elem_assigns.items()}
        saved_void = set(scope.void)
        self._overlay_token = self._overlay_token + (id(expr),)
        try:
            for gen in expr.generators:
                scope._bind_loop(gen.target, gen.iter)
            objs: Set[Path] = set()
            derived: Set[Path] = set()
            elts = (
                [expr.key, expr.value]
                if isinstance(expr, ast.DictComp)
                else [expr.elt]
            )
            for e in elts:
                o, d = self._split(e, fn, depth, visiting)
                objs |= o
                derived |= d
            for gen in expr.generators:
                derived |= self._free(gen.iter, fn, depth, visiting)
                for cond in gen.ifs:
                    derived |= self._free(cond, fn, depth, visiting)
            return objs, derived
        finally:
            self._overlay_token = self._overlay_token[:-1]
            scope.loop_binds = saved_loop
            scope.assigns = saved_assigns
            scope.elem_assigns = saved_elems
            scope.void = saved_void

    def _call_split(
        self, call: ast.Call, fn: FnInfo, depth: int, visiting: frozenset
    ) -> Tuple[Set[Path], Set[Path]]:
        none: Set[Path] = set()
        f = call.func
        # a read from a registered container is cache plumbing, not input
        if isinstance(f, ast.Attribute) and f.attr in ("get",):
            recv = self._receiver_path(f.value, fn)
            if self.registry.for_receiver(recv) is not None:
                return none, none
        # getattr(self, "x", d) -> self.x plus default reads
        if (
            isinstance(f, ast.Name)
            and f.id == "getattr"
            and len(call.args) >= 2
            and _is_const_str(call.args[1]) is not None
        ):
            o, d = self._split(call.args[0], fn, depth, visiting)
            objs = {bp + (_is_const_str(call.args[1]),) for bp in o}
            for extra in call.args[2:]:
                d |= self._free(extra, fn, depth, visiting)
            return objs, d
        # copy-shaped constructors keep the first argument addressable
        if (
            isinstance(f, ast.Name)
            and f.id in self._COPY_CALLS
            and call.args
        ):
            o, d = self._split(call.args[0], fn, depth, visiting)
            for extra in call.args[1:]:
                d |= self._free(extra, fn, depth, visiting)
            for k in call.keywords:
                d |= self._free(k.value, fn, depth, visiting)
            return o, d
        if isinstance(f, ast.Attribute) and f.attr == "copy" and not call.args:
            return self._split(f.value, fn, depth, visiting)
        target = self.resolve_call(call, fn)
        if target is not None and depth < _INLINE_DEPTH:
            body = self._callee_free(target, depth + 1, visiting)
            recv: Optional[Path] = ("self",)
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                if f.value.id not in ("self", "cls"):
                    # module-function via alias: no receiver
                    recv = None
            elif isinstance(f, ast.Name):
                recv = None
            kw = {k.arg: k.value for k in call.keywords if k.arg}
            return none, self._map_paths(
                body, target, list(call.args), kw, recv, fn, depth, visiting
            )
        derived: Set[Path] = set()
        for a in call.args:
            if isinstance(a, ast.Starred):
                a = a.value
            derived |= self._free(a, fn, depth, visiting)
        for k in call.keywords:
            derived |= self._free(k.value, fn, depth, visiting)
        if isinstance(f, ast.Attribute):
            derived |= self._free(f.value, fn, depth, visiting)
        return none, derived

    def _receiver_path(self, expr: ast.AST, fn: FnInfo) -> Optional[Path]:
        """Resolved path of a container receiver, chasing single local
        aliases (``sr = e.sig_rows``)."""
        chain = self._chain(expr)
        if chain is None:
            return None
        base, suffix = chain
        scope = self.scope_for(fn)
        hops = 0
        while (
            not suffix
            and base in scope.assigns
            and len(scope.assigns[base]) == 1
            and hops < 4
        ):
            nxt = self._chain(scope.assigns[base][0])
            if nxt is None:
                break
            base, suffix = nxt[0], nxt[1] + suffix
            hops += 1
        if base in scope.loop_binds and not suffix:
            it, _ = scope.loop_binds[base]
            nxt = self._chain(it)
            if nxt is not None:
                base, suffix = nxt[0], nxt[1] + (_WILD,) + suffix
        return (base,) + suffix

    def _callee_free(
        self, target: FnInfo, depth: int, visiting: frozenset
    ) -> Set[Path]:
        """Free paths of a callee's result: the backward slice of its
        return expressions, or (for procedures) of its whole body."""
        vkey = (id(target.node), "<fn>")
        if vkey in visiting:
            self._guard_fired(vkey)
            return set()
        mkey = (id(target.node), depth, self._key_mode, self._overlay_token)
        hit = self._memo_get(self._callee_memo, mkey, visiting)
        if hit is not None:
            return hit
        self._fired_stack.append(set())
        try:
            out = self._callee_free_uncached(target, depth, visiting | {vkey})
        finally:
            fired = self._fired_stack.pop()
        fired.discard(vkey)  # our own guard key is satisfied by entry
        self._memo_put(self._callee_memo, mkey, out, fired)
        return out

    def _callee_free_uncached(
        self, target: FnInfo, depth: int, visiting: frozenset
    ) -> Set[Path]:
        node = target.node
        stmts = self._fn_size.get(id(node))
        if stmts is None:
            stmts = sum(1 for _ in ast.walk(node))
            self._fn_size[id(node)] = stmts
        if stmts > _INLINE_STMT_CAP * 4:
            # too big to model: reads ~= its parameters
            scope = self.scope_for(target)
            return {(p,) for p in scope.params}
        own = list(_own_nodes(node))
        returns = [
            n.value
            for n in own
            if isinstance(n, ast.Return) and n.value is not None
        ]
        out: Set[Path] = set()
        if returns:
            for r in returns:
                out |= self._free(r, target, depth, visiting)
        else:
            # procedures: every expression statement / call argument
            for stmt in own:
                if isinstance(stmt, ast.Expr):
                    out |= self._free(stmt.value, target, depth, visiting)
                elif isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    v = stmt.value
                    if v is not None:
                        out |= self._free(v, target, depth, visiting)
                elif isinstance(stmt, (ast.If, ast.While)):
                    out |= self._free(stmt.test, target, depth, visiting)
                elif isinstance(stmt, ast.For):
                    out |= self._free(stmt.iter, target, depth, visiting)
        return out

    def _map_paths(
        self,
        body: Set[Path],
        target: FnInfo,
        args: List[ast.AST],
        kwargs: Dict[str, ast.AST],
        recv: Optional[Path],
        fn: FnInfo,
        depth: int,
        visiting: frozenset,
    ) -> Set[Path]:
        """Substitute a callee's formal-rooted paths with caller argument
        paths; ``self``-rooted paths map onto the receiver."""
        node = target.node
        a = node.args
        pos = [p.arg for p in a.posonlyargs + a.args]
        is_method = target.cls is not None and pos and pos[0] in ("self", "cls")
        formals = pos[1:] if is_method else pos
        actual: Dict[str, ast.AST] = {}
        for name, arg in zip(formals, args):
            if isinstance(arg, ast.Starred):
                continue
            actual[name] = arg
        actual.update({k: v for k, v in kwargs.items() if k in set(pos)})
        out: Set[Path] = set()
        for p in body:
            root = p[0]
            if root in ("self", "cls") and is_method:
                if recv is not None:
                    out.add(recv + p[1:] if recv != ("self",) else p)
                continue
            if root in actual:
                for bp in self._free(actual[root], fn, depth, visiting):
                    out.add(bp + p[1:])
                continue
            if root in [x.arg for x in a.kwonlyargs] and root in kwargs:
                for bp in self._free(kwargs[root], fn, depth, visiting):
                    out.add(bp + p[1:])
                continue
            if root in formals or root in [x.arg for x in a.kwonlyargs]:
                continue  # unbound formal (default): no caller reads
            tmi = self.modules.get(target.ctx.relpath)
            if tmi is not None and root in tmi.globals_caps:
                continue
            out.add(p)  # callee-module global
        return out


# ---------------------------------------------------------------------------
# memo-site detection


@dataclass
class CacheEvent:
    kind: str  # 'get' | 'put'
    spec: ContainerSpec
    fn: FnInfo  # host function (after lifting)
    line: int  # line in the host function (marker anchor)
    key_exprs: List[ast.AST] = field(default_factory=list)
    value_exprs: List[ast.AST] = field(default_factory=list)
    guard_exprs: List[ast.AST] = field(default_factory=list)
    owner_expr: Optional[ast.AST] = None
    origin: Optional[int] = None  # helper fn id for lifted events


@dataclass
class Site:
    spec: ContainerSpec
    fn: FnInfo
    gets: List[CacheEvent]
    puts: List[CacheEvent]


def _fn_events(an: Analyzer, fn: FnInfo) -> List[CacheEvent]:
    """Raw get/put events on registered containers inside ``fn``."""
    out: List[CacheEvent] = []
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in ("get", "put", "setdefault"):
                recv = an._receiver_path(node.func.value, fn)
                spec = an.registry.for_receiver(recv)
                if spec is not None and node.args:
                    ev = CacheEvent(
                        "get" if attr == "get" else "put",
                        spec,
                        fn,
                        node.lineno,
                        key_exprs=[node.args[0]],
                        owner_expr=node.func.value,
                    )
                    if attr in ("put", "setdefault") and len(node.args) > 1:
                        ev.value_exprs = [node.args[1]]
                    out.append(ev)
                # pod-memo convention: d.get("_karp_memo")
                elif (
                    attr == "get"
                    and node.args
                    and _is_const_str(node.args[0]) == "_karp_memo"
                ):
                    out.append(
                        CacheEvent(
                            "get",
                            _PODMEMO_SPEC,
                            fn,
                            node.lineno,
                            key_exprs=[],
                            owner_expr=node.func.value,
                        )
                    )
            elif attr in ("seeds_get", "seeds_put") and node.args:
                spec = ContainerSpec("seeds")
                ev = CacheEvent(
                    "get" if attr == "seeds_get" else "put",
                    spec,
                    fn,
                    node.lineno,
                    key_exprs=[node.args[0]],
                )
                if len(node.args) > 1:
                    ev.guard_exprs = [node.args[1]]
                if attr == "seeds_put" and len(node.args) > 2:
                    ev.value_exprs = [node.args[2]]
                out.append(ev)
            elif attr in ("skeleton_get", "skeleton_put") and node.args:
                # fleet content plane accessor pair (fleet/megasolve.py
                # SkeletonPlane): key arg 0, stored skeleton arg 1
                spec = ContainerSpec("fleetjob")
                ev = CacheEvent(
                    "get" if attr == "skeleton_get" else "put",
                    spec,
                    fn,
                    node.lineno,
                    key_exprs=[node.args[0]],
                )
                if attr == "skeleton_put" and len(node.args) > 1:
                    ev.value_exprs = [node.args[1]]
                out.append(ev)
        elif isinstance(node, ast.Assign) and isinstance(
            node.targets[0], ast.Subscript
        ):
            tgt = node.targets[0]
            recv = an._receiver_path(tgt.value, fn)
            spec = an.registry.for_receiver(recv)
            if spec is not None:
                out.append(
                    CacheEvent(
                        "put",
                        spec,
                        fn,
                        node.lineno,
                        key_exprs=[tgt.slice],
                        value_exprs=[node.value],
                        owner_expr=tgt.value,
                    )
                )
            elif _is_const_str(tgt.slice) == "_karp_memo":
                out.append(
                    CacheEvent(
                        "put",
                        _PODMEMO_SPEC,
                        fn,
                        node.lineno,
                        key_exprs=[],
                        value_exprs=[node.value],
                        owner_expr=tgt.value,
                    )
                )
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
            recv = an._receiver_path(node.value, fn)
            spec = an.registry.for_receiver(recv)
            if spec is not None:
                out.append(
                    CacheEvent(
                        "get",
                        spec,
                        fn,
                        node.lineno,
                        key_exprs=[node.slice],
                        owner_expr=node.value,
                    )
                )
    return out


_PODMEMO_SPEC = ContainerSpec("podmemo", owner_scoped=True)


def _skip_fn(fn: FnInfo) -> bool:
    if fn.cls in _PLUMBING_CLASSES:
        return True
    if fn.cls is None and fn.name in _PLUMBING_FNS:
        return True
    return False


def _lift_events(an: Analyzer) -> Dict[Tuple[int, str], Site]:
    """Collect events per function, then lift events out of put-helper
    functions into their callers (``_cache_put``, ``_sig_rows_put``,
    ``_cache_compat_rows`` — any function whose cache events root at its
    own formals), so split sites pair up where the real inputs live."""
    raw: Dict[int, List[CacheEvent]] = {}
    fns: Dict[int, FnInfo] = {}
    for mi in an.modules.values():
        for fi in list(mi.functions.values()) + [
            m for c in mi.classes.values() for m in c.methods.values()
        ]:
            if fi.ctx.relpath not in {f.relpath for f in an.cache_files}:
                continue
            if _skip_fn(fi):
                continue
            fns[id(fi.node)] = fi
            evs = _fn_events(an, fi)
            if evs:
                raw[id(fi.node)] = evs

    def formal_rooted(ev: CacheEvent, fi: FnInfo) -> Optional[Set[str]]:
        """The set of formals an event's key+value read — or None when
        the event also reads non-formal state (not liftable)."""
        scope = an.scope_for(fi)
        roots: Set[str] = set()
        for e in ev.key_exprs + ev.value_exprs + (
            [ev.owner_expr] if ev.owner_expr is not None else []
        ):
            for p in an.free(e, fi):
                r = p[0]
                if r in scope.params and r not in ("self", "cls"):
                    roots.add(r)
                elif r in ("self", "cls"):
                    return None
                else:
                    return None
        return roots

    def classify_helpers() -> Dict[int, List[CacheEvent]]:
        """Put-helper functions: every cache event is a put whose key,
        value and owner root at the function's own formals — callers own
        the real inputs, so the events lift to the call sites."""
        out: Dict[int, List[CacheEvent]] = {}
        for fid, evs in raw.items():
            fi = fns[fid]
            if all(
                ev.kind == "put" and formal_rooted(ev, fi) is not None
                for ev in evs
            ):
                out[fid] = evs
        return out

    helpers: Dict[int, List[CacheEvent]] = {}

    def lift_into_callers(rounds: int) -> None:
        nonlocal helpers
        for _ in range(rounds):
            helpers = classify_helpers()
            changed = False
            for fid, fi in fns.items():
                if fid in helpers:
                    continue  # a helper's own call sites lift elsewhere
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    target = an.resolve_call(node, fi)
                    if target is None or id(target.node) not in helpers:
                        continue
                    if id(target.node) == fid:
                        continue
                    # substitute each helper event's exprs with caller args
                    a = target.node.args
                    pos = [p.arg for p in a.posonlyargs + a.args]
                    is_method = target.cls is not None and pos[:1] == ["self"]
                    formals = pos[1:] if is_method else pos
                    amap = dict(zip(formals, node.args))
                    amap.update(
                        {k.arg: k.value for k in node.keywords if k.arg in formals}
                    )
                    for ev in helpers[id(target.node)]:
                        lifted = CacheEvent(
                            ev.kind,
                            ev.spec,
                            fi,
                            node.lineno,
                            origin=ev.origin or id(target.node),
                        )
                        for bucket, src, extract in (
                            (lifted.key_exprs, ev.key_exprs, an.free_key),
                            (lifted.value_exprs, ev.value_exprs, an.free),
                        ):
                            for e in src:
                                roots = {
                                    p[0]
                                    for p in extract(e, target)
                                    if p[0] in formals
                                }
                                for r in sorted(roots):
                                    if r in amap:
                                        bucket.append(amap[r])
                        if ev.owner_expr is not None:
                            o = an._chain(ev.owner_expr)
                            if o is not None and o[0] in amap:
                                lifted.owner_expr = amap[o[0]]
                        key = id(fi.node)
                        evs2 = raw.setdefault(key, [])
                        marker = (ev.spec.name, node.lineno, ev.kind)
                        if not any(
                            (e2.spec.name, e2.line, e2.kind) == marker
                            for e2 in evs2
                        ):
                            evs2.append(lifted)
                            changed = True
            if not changed:
                return

    lift_into_callers(_INLINE_DEPTH)

    sites: Dict[Tuple[int, str], Site] = {}
    for fid, evs in raw.items():
        if fid in helpers:
            continue  # analyzed at the lifted site
        fi = fns[fid]
        by_spec: Dict[str, List[CacheEvent]] = {}
        for ev in evs:
            by_spec.setdefault(ev.spec.name, []).append(ev)
        for cname, group in by_spec.items():
            puts = [e for e in group if e.kind == "put"]
            gets = [e for e in group if e.kind == "get"]
            if not puts:
                continue
            own_puts = [e for e in puts if e.origin is None]
            if own_puts:
                # lifted puts are a DIFFERENT code path (e.g. a replay
                # helper re-caching from a skeleton): keep them as their
                # own site so they cannot witness the main site's reads
                sites[(fid, cname)] = Site(puts[0].spec, fi, gets, own_puts)
                lifted = [e for e in puts if e.origin is not None]
                by_origin: Dict[int, List[CacheEvent]] = {}
                for e in lifted:
                    by_origin.setdefault(e.origin, []).append(e)
                for origin, group2 in by_origin.items():
                    sites[(fid, f"{cname}#{origin}")] = Site(
                        group2[0].spec, fi, [], group2
                    )
            else:
                sites[(fid, cname)] = Site(puts[0].spec, fi, gets, puts)
    return sites


# ---------------------------------------------------------------------------
# rule 1: cache-key (key-completeness)

# cache plumbing that is never a solve input
_PLUMBING_SELF_ATTRS = {"_cstats", "_warm", "_seed_cache"}
_PLUMBING_NAMES = {"stats", "tracer", "ws"}


#: analyzers reused across runs while their module set's parsed trees
#: are identical (the engine parse cache hands back the same tree object
#: for an unchanged file, so tree identity IS content identity) — the
#: mutation harness and the tier-1 meta-tests re-analyze near-identical
#: sets dozens of times
_ANALYZERS: Dict[frozenset, Analyzer] = {}


def _shared_analyzer(pctx: ProjectContext) -> Analyzer:
    an = getattr(pctx, "_cachesound", None)
    if an is not None:
        return an
    cfg = pctx.config
    probe = pctx.matching(
        tuple(cfg.cache_modules)
        + tuple(cfg.state_modules)
        + tuple(cfg.provider_modules)
        + tuple(cfg.cluster_consumer_modules)
    )
    key = frozenset((f.relpath, id(f.tree)) for f in probe)
    an = _ANALYZERS.get(key)
    if an is None:
        an = Analyzer(pctx)
        if len(_ANALYZERS) >= 8:
            _ANALYZERS.clear()
        _ANALYZERS[key] = an
    pctx._cachesound = an
    return an


def _shared_sites(an: Analyzer) -> Dict[Tuple[int, str], Site]:
    sites = getattr(an, "_sites", None)
    if sites is None:
        sites = _lift_events(an)
        an._sites = sites
    return sites


def _marker_exclusions(site: Site) -> List[Path]:
    out: List[Path] = []
    lines = site.fn.ctx.lines
    for ev in site.gets + site.puts:
        args = scoped_marker_args(lines, ev.line, "cache-key")
        if args:
            out.extend(parse_marker_path(a) for a in args)
    return out


def _witness_of(an: Analyzer, events: List[CacheEvent]) -> Set[Path]:
    out: Set[Path] = set()
    for ev in events:
        for e in ev.key_exprs:
            out |= an.free_key(e, ev.fn)
        for e in ev.guard_exprs:
            out |= an.free_key(e, ev.fn)
    return out


def _drop_plumbing(paths: Set[Path], receivers: Set[str]) -> Set[Path]:
    out = set()
    for p in paths:
        if not p:
            continue
        if p[0] in _BENIGN_ROOTS or p[0] in _PLUMBING_NAMES or p[0] in receivers:
            continue
        if len(p) > 1 and p[1] in _PLUMBING_SELF_ATTRS:
            continue
        out.add(p)
    return out


def _minimal(paths: Set[Path]) -> Set[Path]:
    """Shortest-prefix form: a read of ``x`` subsumes ``x.anything``."""
    out: Set[Path] = set()
    for p in sorted(paths, key=len):
        if not any(len(q) < len(p) and paths_match(q, p) for q in out):
            out.add(p)
    return out


def _check_site(an: Analyzer, site: Site) -> Iterable[Finding]:
    fn = site.fn
    receivers: Set[str] = set()
    for ev in site.gets + site.puts:
        if ev.owner_expr is not None:
            rp = an._receiver_path(ev.owner_expr, fn)
            if rp:
                receivers.add(rp[0])
    witness_get = _drop_plumbing(_witness_of(an, site.gets), receivers)
    witness_put = _drop_plumbing(_witness_of(an, site.puts), receivers)
    witness = witness_get | witness_put
    # owner-scoped containers: the owner object is a content address
    # (catalog entries, encodings, the pod itself) — its root witnesses
    # everything reachable from it
    if site.spec.owner_scoped:
        for ev in site.gets + site.puts:
            if ev.owner_expr is not None:
                for p in an.free(ev.owner_expr, fn):
                    witness.add((p[0],))
    exclusions = _marker_exclusions(site)
    put_line = max(ev.line for ev in site.puts)

    def excluded(path: Path) -> bool:
        # declared exclusions compare against the wildcard-stripped path:
        # allow-cache-key(meta.alloc) covers meta[*]["alloc"] but must not
        # swallow meta[*]["reqs"]
        squeezed = tuple(part for part in path if part != _WILD)
        return any(squeezed[: len(e)] == e for e in exclusions)

    # -- split-site key drift: get and put must witness the same roots --
    if site.gets and witness_get and witness_put:
        g_roots = {rootkey(p) for p in witness_get}
        p_roots = {rootkey(p) for p in witness_put}
        for root in sorted(g_roots ^ p_roots):
            if excluded(root):
                continue
            side = "get" if root in g_roots else "put"
            other = "put" if side == "get" else "get"
            yield Finding(
                rule="cache-key",
                path=fn.ctx.relpath,
                line=put_line,
                symbol=fn.symbol,
                message=(
                    f"cache '{site.spec.name}': key input '{render(root)}' is "
                    f"witnessed by the {side} key but not the {other} key — "
                    f"split-site key drift serves entries across a changed input"
                ),
                severity=SEV_ERROR,
            )

    # -- read-set vs witness --------------------------------------------
    reads: Set[Path] = set()
    for ev in site.puts:
        for e in ev.value_exprs:
            reads |= an.free(e, fn)
    # side effects: calls in the get..put region that share state with
    # the slice feed the cached value through mutation
    lo = min(ev.line for ev in site.gets + site.puts)
    hi = put_line
    for _ in range(2):
        roots = {rootkey(p) for p in reads}
        for node in ast.walk(fn.node):
            if (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and lo <= node.lineno <= hi
            ):
                a = an.free(node.value, fn)
                if {rootkey(p) for p in a} & roots:
                    reads |= a
    reads = _minimal(_drop_plumbing(reads, receivers))

    # tenant-scope witness (ISSUE 9): generation-guarded caches that can
    # serve multiple tenants must carry the tenant scope in their key —
    # the generation guard is a per-object counter, so equal values from
    # two tenants' objects would otherwise alias their entries
    if site.spec.name in _TENANT_SCOPED_SPECS:
        raw_witness = _witness_of(an, site.gets + site.puts)
        has_tenant = any(
            seg in _TENANT_WITNESS_SEGMENTS for p in raw_witness for seg in p
        )
        if not has_tenant and not excluded(("_tenant_scope",)):
            yield Finding(
                rule="cache-key",
                path=fn.ctx.relpath,
                line=put_line,
                symbol=fn.symbol,
                message=(
                    f"cache '{site.spec.name}': key does not witness the tenant "
                    f"scope — its generation guard is a per-tenant counter, so "
                    f"equal generations from different tenants' objects would "
                    f"alias entries across tenants (add the solver's "
                    f"_tenant_scope / the tenant id to the key)"
                ),
                severity=SEV_ERROR,
            )

    # pod-memo rv guard: the stored tuple's first element must witness
    # the pod's resource_version (the memo's only validity check)
    if site.spec.name == "podmemo":
        for ev in site.puts:
            ok = False
            for e in ev.value_exprs:
                if isinstance(e, ast.Tuple) and e.elts:
                    for p in an.free(e.elts[0], fn):
                        if p and p[-1] == "resource_version":
                            ok = True
            if not ok and not excluded(("resource_version",)):
                yield Finding(
                    rule="cache-key",
                    path=fn.ctx.relpath,
                    line=ev.line,
                    symbol=fn.symbol,
                    message=(
                        "cache 'podmemo': stored memo does not witness the "
                        "pod's resource_version — in-place spec mutation "
                        "would serve a stale memo"
                    ),
                    severity=SEV_ERROR,
                )

    seen: Set[Path] = set()
    for p in sorted(reads):
        if p in seen:
            continue
        seen.add(p)
        if excluded(p):
            continue
        if any(paths_match(p, w) for w in witness):
            continue
        yield Finding(
            rule="cache-key",
            path=fn.ctx.relpath,
            line=put_line,
            symbol=fn.symbol,
            message=(
                f"cache '{site.spec.name}': input '{render(p)}' is read by the "
                f"cached computation but not witnessed by the key — add it to "
                f"the key, guard it with a generation, or declare "
                f"`# analysis: allow-cache-key({render(p)}) — <why sound>`"
            ),
            severity=SEV_ERROR,
        )


@project_rule(
    "cache-key",
    "every cross-solve memo key must witness the cached computation's read-set",
)
def check_cache_key(pctx: ProjectContext):
    an = _shared_analyzer(pctx)
    sites = _shared_sites(an)
    out: List[Finding] = []
    for _, site in sorted(sites.items(), key=lambda kv: (kv[1].fn.ctx.relpath, kv[1].fn.symbol, kv[0][1])):
        out.extend(_check_site(an, site))
    dedup: Dict[tuple, Finding] = {}
    for f in out:
        dedup.setdefault((f.path, f.symbol, f.message), f)
    yield from sorted(dedup.values(), key=lambda f: (f.path, f.line, f.message))


# ---------------------------------------------------------------------------
# rule 2: cache-invalidation (invalidation-completeness)

_WRITE_METHOD_PREFIXES = (
    "update_", "set_", "add_", "remove_", "delete_", "cleanup_", "clear",
    "mark_", "unmark_", "pop_", "insert_", "carry_",
)
_MUTATOR_CALLS = {
    "append", "extend", "insert", "pop", "popitem", "remove", "discard",
    "add", "clear", "update", "setdefault", "sort", "reverse",
    "appendleft", "popleft",
}
_EXEMPT = {"__init__", "__new__", "__post_init__"}


def _gen_fields(ci: ClassInfo, gen_method: str) -> Set[str]:
    m = ci.methods.get(gen_method)
    if m is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(m.node):
        if isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                ):
                    out.add(sub.attr)
    return out


def _writes_gen(an: Analyzer, m: FnInfo, gen_fields: Set[str]) -> bool:
    """A method bumps when it writes a generation field with a value
    derived from the field itself (+=, old+1 read through generation(),
    verified by dataflow) — a plain constant write is a RESET that can
    repeat past values, not a bump. Writing None is accepted: it
    deactivates the generation and hands invalidation back to content
    fingerprinting."""
    for node in ast.walk(m.node):
        tgt = None
        if isinstance(node, ast.AugAssign):
            tgt, val = node.target, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
        else:
            continue
        if not (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
            and tgt.attr in gen_fields
        ):
            continue
        if isinstance(node, ast.AugAssign):
            return True
        if isinstance(val, ast.Constant) and val.value is None:
            return True  # deactivates the generation: fingerprint resumes
        for p in an.free(val, m):
            if p[:1] == ("self",) and len(p) > 1 and p[1] in gen_fields:
                return True
    return False


def _self_calls(m: FnInfo) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(m.node):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if (
                isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
            ):
                out.add(node.func.attr)
    return out


def _fields_read(ci: ClassInfo, method: str, depth: int = 0) -> Set[str]:
    m = ci.methods.get(method)
    if m is None or depth > 2:
        return set()
    out: Set[str] = set()
    for node in ast.walk(m.node):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            if node.attr in ci.methods:
                if node.attr != method:
                    out |= _fields_read(ci, node.attr, depth + 1)
            else:
                out.add(node.attr)
    return out


@dataclass
class _MethodWrites:
    fields: Set[str] = field(default_factory=set)
    first_line: int = 0


def _method_writes(an: Analyzer, m: FnInfo, relevant: Set[str]) -> _MethodWrites:
    """Relevant fields ``m`` writes: direct stores, subscript stores,
    mutator calls, and write-shaped calls/stores through local aliases
    of relevant fields."""
    w = _MethodWrites()

    def hit(f: str, line: int) -> None:
        if f in relevant:
            w.fields.add(f)
            if not w.first_line or line < w.first_line:
                w.first_line = line

    tainted: Dict[str, str] = {}  # local name -> field it aliases
    for node in ast.walk(m.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    roots = {
                        p[:2]
                        for p in an.free(node.value, m, depth=_INLINE_DEPTH)
                        if p[:1] == ("self",) and len(p) > 1
                    }
                    for r in roots:
                        if r[1] in relevant:
                            tainted[t.id] = r[1]
                elif isinstance(t, ast.Attribute):
                    if isinstance(t.value, ast.Name):
                        if t.value.id == "self":
                            hit(t.attr, node.lineno)
                        elif t.value.id in tainted:
                            hit(tainted[t.value.id], node.lineno)
                elif isinstance(t, ast.Subscript):
                    base = t.value
                    if (
                        isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                    ):
                        hit(base.attr, node.lineno)
                    elif isinstance(base, ast.Name) and base.id in tainted:
                        hit(tainted[base.id], node.lineno)
        elif isinstance(node, ast.AugAssign):
            t = node.target
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                hit(t.attr, node.lineno)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                sub = t
                while isinstance(sub, ast.Subscript):
                    sub = sub.value
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                ):
                    hit(sub.attr, node.lineno)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            f = node.func
            name = f.attr
            recv = f.value
            if name in _MUTATOR_CALLS or name.startswith(_WRITE_METHOD_PREFIXES):
                base = recv
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    if (
                        isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                    ):
                        if base.attr in relevant:
                            hit(base.attr, node.lineno)
                        base = None
                        break
                    base = base.value
                if isinstance(base, ast.Name) and base.id in tainted:
                    hit(tainted[base.id], node.lineno)
    return w


def _check_generation_class(
    an: Analyzer,
    ci: ClassInfo,
    gen_method: str,
    relevant: Set[str],
    kind: str,
) -> Iterable[Finding]:
    gen_fields = _gen_fields(ci, gen_method)
    if not gen_fields:
        return
    relevant = relevant - gen_fields
    bumpers = {
        name
        for name, m in ci.methods.items()
        if _writes_gen(an, m, gen_fields)
    }
    calls = {name: _self_calls(m) for name, m in ci.methods.items()}
    # transitive bump closure over intra-class calls
    changed = True
    while changed:
        changed = False
        for name, callees in calls.items():
            if name not in bumpers and callees & bumpers:
                bumpers.add(name)
                changed = True

    writes: Dict[str, _MethodWrites] = {}
    for name, m in ci.methods.items():
        if name in _EXEMPT or name == gen_method:
            continue
        mw = _method_writes(an, m, relevant)
        if mw.fields:
            writes[name] = mw

    # private helpers whose intra-class callers ALL bump are covered
    callers: Dict[str, List[str]] = {}
    for caller, callees in calls.items():
        for callee in callees:
            callers.setdefault(callee, []).append(caller)
    covered = set()
    changed = True
    while changed:
        changed = False
        for name in writes:
            if name in bumpers or name in covered:
                continue
            if not name.startswith("_"):
                continue
            cs = callers.get(name, [])
            if cs and all(
                c in bumpers or c in covered or c in _EXEMPT for c in cs
            ):
                covered.add(name)
                changed = True

    for name, mw in sorted(writes.items()):
        if name in bumpers or name in covered:
            continue
        m = ci.methods[name]
        fields = ", ".join(f"'{f}'" for f in sorted(mw.fields))
        yield Finding(
            rule="cache-invalidation",
            path=ci.ctx.relpath,
            line=mw.first_line or m.node.lineno,
            symbol=m.symbol,
            message=(
                f"{kind} mutator writes {fields} (observable by cross-solve "
                f"caches) without bumping {gen_method}() — a warm solve keyed "
                f"on the stale generation would replay pre-mutation state"
            ),
            severity=SEV_ERROR,
        )


@project_rule(
    "cache-invalidation",
    "informer/catalog mutators must bump the generation their caches key on",
)
def check_cache_invalidation(pctx: ProjectContext):
    an = _shared_analyzer(pctx)
    cfg = pctx.config
    # generation-relevant cluster fields = what the consumer modules
    # actually reach through the cluster API
    consumer_ctxs = pctx.matching(cfg.cluster_consumer_modules)
    api: Set[str] = set()
    for ctx in consumer_ctxs:
        for node in ctx.walk():
            if isinstance(node, ast.Attribute):
                dn = dotted_name(node)
                if dn:
                    parts = dn.split(".")
                    for i, part in enumerate(parts[:-1]):
                        if part == "cluster":
                            api.add(parts[i + 1])
    out: List[Finding] = []
    for relpath in sorted(an.modules):
        mi = an.modules[relpath]
        in_state = any(relpath.endswith(s) for s in cfg.state_modules)
        in_provider = any(relpath.endswith(s) for s in cfg.provider_modules)
        fixture = not relpath.startswith("karpenter_core_tpu/")
        if not (in_state or in_provider or fixture):
            continue
        for ci in mi.classes.values():
            if "generation" in ci.methods and (in_state or fixture):
                relevant: Set[str] = set()
                for a in api:
                    if a in ci.methods:
                        relevant |= _fields_read(ci, a)
                    else:
                        relevant.add(a)
                relevant -= {m for m in ci.methods}
                if relevant:
                    out.extend(
                        _check_generation_class(
                            an, ci, "generation", relevant, "informer-state"
                        )
                    )
            if "catalog_generation" in ci.methods and (in_provider or fixture):
                relevant = _fields_read(ci, "get_instance_types")
                relevant -= {m for m in ci.methods}
                if relevant:
                    out.extend(
                        _check_generation_class(
                            an, ci, "catalog_generation", relevant, "catalog"
                        )
                    )
    yield from sorted(out, key=lambda f: (f.path, f.line, f.message))


# ---------------------------------------------------------------------------
# rule 3: cache-determinism (key-determinism)

_NAME_CONTEXT_RE = re.compile(
    r"fingerprint|digest|signature|intern|(^|_)key(s)?($|_)"
)


def _slice_nodes(
    an: Analyzer,
    expr: ast.AST,
    fn: FnInfo,
    depth: int,
    out: List[Tuple[FnInfo, ast.AST]],
    visited: Set[int],
) -> None:
    """Syntactic slice: the expression, the assignments its names chase
    to, and (depth-limited) the bodies of resolvable key-builder calls —
    the nodes whose constructs determine the key's process stability."""
    if id(expr) in visited:
        return
    visited.add(id(expr))
    scope = an.scope_for(fn)
    for node in ast.walk(expr):
        out.append((fn, node))
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            for v in scope.assigns.get(node.id, []):
                _slice_nodes(an, v, fn, depth, out, visited)
            for v in scope.elem_assigns.get(node.id, []):
                # a tuple/list literal flowing into a container lost its
                # positions (x rode the container next to unrelated
                # values) — descending would attribute every sibling
                # element's constructs to this key
                if isinstance(v, (ast.Tuple, ast.List)):
                    continue
                _slice_nodes(an, v, fn, depth, out, visited)
            lb = scope.loop_binds.get(node.id)
            if lb is not None and not isinstance(lb[0], (ast.Tuple, ast.List)):
                _slice_nodes(an, lb[0], fn, depth, out, visited)
        elif isinstance(node, ast.Call) and depth < _INLINE_DEPTH:
            target = an.resolve_call(node, fn)
            if target is not None and id(target.node) not in visited:
                visited.add(id(target.node))
                for sub in ast.walk(target.node):
                    if isinstance(sub, ast.Return) and sub.value is not None:
                        _slice_nodes(an, sub.value, target, depth + 1, out, visited)


def _set_typed(an: Analyzer, expr: ast.AST, fn: FnInfo, hops: int = 0) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return True
        if isinstance(f, ast.Attribute) and f.attr in (
            "intersection", "union", "difference", "symmetric_difference",
            "keys_set",
        ):
            return True
        return False
    if isinstance(expr, ast.BinOp) and isinstance(
        expr.op, (ast.BitAnd, ast.BitOr, ast.Sub)
    ):
        return _set_typed(an, expr.left, fn, hops) or _set_typed(
            an, expr.right, fn, hops
        )
    if isinstance(expr, ast.Name) and hops < 3:
        scope = an.scope_for(fn)
        vals = scope.assigns.get(expr.id, [])
        return bool(vals) and all(
            _set_typed(an, v, fn, hops + 1)
            or (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                and v.func.attr in ("copy", "union", "intersection"))
            for v in vals
        )
    return False


def _float_evidence(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, float):
            return True
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Div):
            return True
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "float"
        ):
            return True
    return False


def _det_allowed(fn: FnInfo, line: int, token: str) -> bool:
    args = scoped_marker_args(fn.ctx.lines, line, "cache-determinism")
    return bool(args) and token in args


def _det_findings_for_context(
    an: Analyzer,
    nodes: List[Tuple[FnInfo, ast.AST]],
    where: str,
) -> Iterable[Finding]:
    producers = set()
    for f in an.cache_files:
        producers |= set(f.config.device_producers)

    def finding(fn: FnInfo, line: int, msg: str) -> Finding:
        return Finding(
            rule="cache-determinism",
            path=fn.ctx.relpath,
            line=line,
            symbol=fn.symbol,
            message=msg,
            severity=SEV_ERROR,
        )

    for fn, node in nodes:
        if isinstance(node, ast.Call):
            f = node.func
            fname = f.id if isinstance(f, ast.Name) else ""
            if fname == "id" and not _det_allowed(fn, node.lineno, "id"):
                yield finding(
                    fn,
                    node.lineno,
                    f"id() in {where} is a process address, not a content "
                    f"address — a recycled id aliases a freed object onto a "
                    f"live key; hold a strong ref + revalidate, then declare "
                    f"`# analysis: allow-cache-determinism(id) — <why>`",
                )
            elif fname in ("tuple", "list", "frozenset") and node.args:
                if _set_typed(an, node.args[0], fn) and not _det_allowed(
                    fn, node.lineno, "set-iteration"
                ):
                    yield finding(
                        fn,
                        node.lineno,
                        f"set iteration order reaches {where} — wrap in "
                        f"sorted() (PYTHONHASHSEED reorders sets across "
                        f"processes)",
                    )
            elif fname == "repr" and node.args and not isinstance(
                node.args[0], ast.Constant
            ):
                if not _det_allowed(fn, node.lineno, "repr"):
                    yield finding(
                        fn,
                        node.lineno,
                        f"repr() of an object in {where} embeds memory "
                        f"addresses/ordering artifacts — use an explicit "
                        f"content tuple",
                    )
            elif fname == "str" and node.args and not isinstance(
                node.args[0], ast.Constant
            ):
                if _float_evidence(node.args[0]) and not _det_allowed(
                    fn, node.lineno, "float"
                ):
                    yield finding(
                        fn,
                        node.lineno,
                        f"float stringification in {where} — normalize "
                        f"floats (struct.pack / float.hex / stablehash) "
                        f"before digesting",
                    )
            elif fname in producers and not _det_allowed(
                fn, node.lineno, "traced"
            ):
                yield finding(
                    fn,
                    node.lineno,
                    f"device/traced value from '{fname}' flows into {where} "
                    f"— a traced value in a key is a tracer leak AND a "
                    f"soundness bug (sync to host + normalize first)",
                )
            elif fname == "map" and node.args and isinstance(
                node.args[0], ast.Name
            ) and node.args[0].id == "id":
                if not _det_allowed(fn, node.lineno, "id"):
                    yield finding(
                        fn,
                        node.lineno,
                        f"id() in {where} is a process address, not a content "
                        f"address — a recycled id aliases a freed object onto a "
                        f"live key; hold a strong ref + revalidate, then declare "
                        f"`# analysis: allow-cache-determinism(id) — <why>`",
                    )
        elif isinstance(node, ast.FormattedValue) and node.conversion == 114:
            if not _det_allowed(fn, node.lineno, "repr"):
                yield finding(
                    fn,
                    node.lineno,
                    f"!r formatting in {where} embeds memory addresses — "
                    f"use an explicit content tuple",
                )
        elif isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            for gen in node.generators:
                if _set_typed(an, gen.iter, fn) and not _det_allowed(
                    fn, node.lineno, "set-iteration"
                ):
                    yield finding(
                        fn,
                        node.lineno,
                        f"set iteration order reaches {where} — wrap in "
                        f"sorted() (PYTHONHASHSEED reorders sets across "
                        f"processes)",
                    )


@project_rule(
    "cache-determinism",
    "no process-unstable material (hash()/id()/set order/repr/raw floats/traced values) in cache keys or digests",
)
def check_cache_determinism(pctx: ProjectContext):
    an = _shared_analyzer(pctx)
    out: List[Finding] = []

    # builtin hash() anywhere in the cache modules: content addresses
    # here must survive a process restart, and hash() never does
    for f in an.cache_files:
        symbols: Dict[ast.AST, str] = {}

        def sym_walk(node: ast.AST, cur: str) -> None:
            for child in ast.iter_child_nodes(node):
                nxt = cur
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    nxt = f"{cur}.{child.name}" if cur else child.name
                symbols[child] = nxt
                sym_walk(child, nxt)

        sym_walk(f.tree, "")
        for node in f.walk():
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "hash"
            ):
                args = scoped_marker_args(f.lines, node.lineno, "cache-determinism")
                if args and "hash" in args:
                    continue
                out.append(
                    Finding(
                        rule="cache-determinism",
                        path=f.relpath,
                        line=node.lineno,
                        symbol=symbols.get(node, ""),
                        message=(
                            "builtin hash() in a cache module is salted per "
                            "process (PYTHONHASHSEED) — use "
                            "solver/stablehash.stable_hash for content "
                            "fingerprints"
                        ),
                        severity=SEV_ERROR,
                    )
                )

    # key slices of every detected memo site
    sites = _shared_sites(an)
    ctx_nodes: List[Tuple[FnInfo, ast.AST]] = []
    visited: Set[int] = set()
    for _, site in sorted(
        sites.items(), key=lambda kv: (kv[1].fn.ctx.relpath, kv[1].fn.symbol, kv[0][1])
    ):
        for ev in site.gets + site.puts:
            for e in ev.key_exprs + ev.guard_exprs:
                _slice_nodes(an, e, site.fn, 0, ctx_nodes, visited)
    out.extend(
        _det_findings_for_context(an, ctx_nodes, "key/digest construction")
    )

    # named key/digest builders (fingerprint, digest, signature, intern)
    named: List[Tuple[FnInfo, ast.AST]] = []
    for relpath in sorted(an.modules):
        if relpath not in {f.relpath for f in an.cache_files}:
            continue
        mi = an.modules[relpath]
        for fi in list(mi.functions.values()) + [
            m for c in mi.classes.values() for m in c.methods.values()
        ]:
            if _skip_fn(fi) or not _NAME_CONTEXT_RE.search(fi.name):
                continue
            for node in ast.walk(fi.node):
                named.append((fi, node))
    out.extend(_det_findings_for_context(an, named, "key/digest construction"))

    dedup: Dict[tuple, Finding] = {}
    for f in out:
        dedup.setdefault((f.path, f.line, f.symbol, f.message), f)
    yield from sorted(
        dedup.values(), key=lambda f: (f.path, f.line, f.message)
    )


# ---------------------------------------------------------------------------
# rule 4: cache-persist (persisted-key re-anchoring, ISSUE 13)
#
# solver/warmstore.py serializes the memo planes to disk and restores
# them into a DIFFERENT process. The in-memory rules above prove keys
# witness their read-sets; persistence adds five ways to break the
# same invariant that no in-memory analysis can see:
#
# - trusting a PERSISTED generation counter: generation guards are
#   per-process ordinals — a restore must re-anchor to the LIVE world's
#   counter (after a content-witness check), never install the dead
#   process's value;
# - dropping the tenant scope while rebinding persisted keys: a
#   restored entry whose key lost its scope aliases scope-free lookups
#   onto another tenant's state;
# - trusting a payload without verifying the writer's schema id and
#   key-layout contract hash: a reader that re-anchors keys it would
#   misparse restores garbage silently;
# - restoring the compile-cache plane (ISSUE 17) without comparing the
#   stored jax/jaxlib/platform fingerprint against the live process —
#   foreign XLA executables are the one payload whose digests cannot
#   witness compatibility, only provenance;
# - restoring the warm-dual plane (ISSUE 19) without parsing its key
#   components as what the writer's contract claims — a price-table
#   fingerprint that isn't a finite float table, or an iteration
#   budget that isn't a sane int, lands duals under keys a live solve
#   could alias after a budget or price-model change.


_PAYLOAD_PARAM_RE = re.compile(
    r"(^|_)(payload|plane|snap|snapshot|entries|handoff|blob)($|_)"
)


def _payload_params(fn_node: ast.AST) -> Set[str]:
    """Parameter names that carry persisted (snapshot-side) data, by
    the warmstore naming contract."""
    out: Set[str] = set()
    args = getattr(fn_node, "args", None)
    if args is None:
        return out
    for a in list(args.args) + list(args.kwonlyargs) + list(args.posonlyargs or []):
        if _PAYLOAD_PARAM_RE.search(a.arg):
            out.add(a.arg)
    return out


def _warmstore_functions(f: FileContext):
    """(symbol, FunctionDef) pairs, nested included."""
    out = []

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sym = f"{prefix}.{child.name}" if prefix else child.name
                out.append((sym, child))
                walk(child, sym)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}.{child.name}" if prefix else child.name)

    walk(f.tree, "")
    return out


def _module_constant_names(f: FileContext) -> Set[str]:
    names: Set[str] = set()
    for node in f.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


@project_rule(
    "cache-persist",
    "persisted cache planes re-anchor on restore: live generations only, tenant scope preserved, schema/contract verified",
)
def check_cache_persist(pctx: ProjectContext):
    files = pctx.matching(pctx.config.warmstore_modules)
    for f in files:
        fns = _warmstore_functions(f)

        # (1) generation re-anchoring: any write to a ``seed_generation``
        # attribute must not be rooted in a persisted payload — the
        # stored counter value is another process's ordinal and
        # witnesses nothing in this one
        for sym, fn_node in fns:
            payload = _payload_params(fn_node)
            if not payload:
                continue
            for node in ast.walk(fn_node):
                if not isinstance(node, (ast.Assign, ast.AugAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                if not any(
                    isinstance(t, ast.Attribute) and t.attr == "seed_generation"
                    for t in targets
                ):
                    continue
                roots = {
                    n.id
                    for n in ast.walk(node.value)
                    if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                }
                bad = roots & payload
                if bad:
                    yield Finding(
                        rule="cache-persist",
                        path=f.relpath,
                        line=node.lineno,
                        symbol=sym,
                        message=(
                            f"restore trusts the PERSISTED generation counter "
                            f"(rooted at {sorted(bad)}) — generation guards are "
                            f"per-process ordinals; re-anchor to the live "
                            f"world's generation after its content witness "
                            f"checks out"
                        ),
                        severity=SEV_ERROR,
                    )

        # (2) tenant-scope preservation: a restore/rebind helper that
        # takes the snapshot's tenant scope must actually thread it into
        # the keys it rebuilds — an unused scope parameter means the
        # restored keys silently dropped their tenant
        for sym, fn_node in fns:
            args = fn_node.args
            scope_params = [
                a.arg
                for a in list(args.args) + list(args.kwonlyargs)
                if a.arg == "tenant_scope" or a.arg.endswith("_tenant_scope")
            ]
            if not scope_params:
                continue
            used = {
                n.id
                for n in ast.walk(fn_node)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
            }
            for p in scope_params:
                if p not in used:
                    yield Finding(
                        rule="cache-persist",
                        path=f.relpath,
                        line=fn_node.lineno,
                        symbol=sym,
                        message=(
                            f"'{p}' is never threaded into the rebuilt keys — "
                            f"restored entries would drop their tenant scope, "
                            f"and a scope-free lookup would alias another "
                            f"tenant's persisted state"
                        ),
                        severity=SEV_ERROR,
                    )

        # (3) contract verification: a module that declares a snapshot
        # schema/contract must compare BOTH against every payload it
        # reads (somewhere in the module) — a reader that skips either
        # check re-anchors keys it may misparse
        consts = _module_constant_names(f)
        declared = {c for c in ("SCHEMA", "CONTRACT") if c in consts}
        if declared and any(
            sym.split(".")[-1].startswith(("read_", "restore")) for sym, _ in fns
        ):
            compared: Set[str] = set()
            for node in f.walk():
                if isinstance(node, ast.Compare):
                    for n in ast.walk(node):
                        if isinstance(n, ast.Name) and n.id in declared:
                            compared.add(n.id)
            for missing in sorted(declared - compared):
                yield Finding(
                    rule="cache-persist",
                    path=f.relpath,
                    line=1,
                    symbol="",
                    message=(
                        f"snapshot reader never compares a payload against "
                        f"{missing} — version/key-layout drift would restore "
                        f"entries the reader misparses (drop the whole "
                        f"snapshot on mismatch, and count it)"
                    ),
                    severity=SEV_ERROR,
                )

        # (4) compile-cache fingerprint witnessing (ISSUE 17): a restore
        # unit that handles the "compilecache" plane is trusting another
        # process's XLA executables — it must take the live environment
        # fingerprint (compile_cache_fingerprint) AND actually compare
        # the jax/jaxlib/platform components against the stored ones. A
        # restore that skips the comparison would replay executables
        # compiled by a different jaxlib onto this process's runtime —
        # the one corruption the byte-level digests cannot see, because
        # the stored digests still match the stored bytes
        for sym, fn_node in fns:
            leaf = sym.split(".")[-1]
            if not leaf.startswith(("restore", "_restore")):
                continue
            touches_plane = any(
                isinstance(n, ast.Constant) and n.value == "compilecache"
                for n in ast.walk(fn_node)
            )
            if not touches_plane:
                continue
            takes_fingerprint = any(
                isinstance(n, ast.Call)
                and (
                    (isinstance(n.func, ast.Attribute) and n.func.attr == "compile_cache_fingerprint")
                    or (isinstance(n.func, ast.Name) and n.func.id == "compile_cache_fingerprint")
                )
                for n in ast.walk(fn_node)
            )
            compares_env = any(
                isinstance(node, ast.Compare)
                and any(
                    isinstance(n, ast.Constant) and n.value in ("jax", "jaxlib", "platform")
                    for n in ast.walk(node)
                )
                for node in ast.walk(fn_node)
            )
            if takes_fingerprint and compares_env:
                continue
            missing_bits = []
            if not takes_fingerprint:
                missing_bits.append("never takes the live compile_cache_fingerprint")
            if not compares_env:
                missing_bits.append(
                    "never compares the stored jax/jaxlib/platform against the live ones"
                )
            yield Finding(
                rule="cache-persist",
                path=f.relpath,
                line=fn_node.lineno,
                symbol=sym,
                message=(
                    "compile-cache plane restored blind: "
                    + " and ".join(missing_bits)
                    + " — a snapshot from a different jax/jaxlib/platform "
                    "would replay foreign XLA executables (drop the plane "
                    "counted on mismatch, never trust it)"
                ),
                severity=SEV_ERROR,
            )

        # (5) warm-dual plane witnessing (ISSUE 19): a restore unit
        # that handles the "lprelax" plane installs another process's
        # converged dual weights as memo values keyed by a price-table
        # fingerprint and an iteration budget. Both key components must
        # be witnessed before a row lands: the price bytes must parse
        # as a FINITE float table (a non-finite price in the key means
        # the stored bound certifies a price model the live guard never
        # prices with), and the iteration budget must survive a sanity
        # comparison (the budget is a first-class key/job-token
        # component — restoring rows with a bogus budget would let a
        # future budget change alias a foreign solve's duals)
        for sym, fn_node in fns:
            leaf = sym.split(".")[-1]
            if not leaf.startswith(("restore", "_restore")):
                continue
            touches_plane = any(
                isinstance(n, ast.Constant) and n.value == "lprelax"
                for n in ast.walk(fn_node)
            )
            if not touches_plane:
                continue
            witnesses_prices = any(
                isinstance(n, ast.Call)
                and (
                    (isinstance(n.func, ast.Attribute) and n.func.attr == "isfinite")
                    or (isinstance(n.func, ast.Name) and n.func.id == "isfinite")
                )
                # the finiteness witness must hold the PRICE table —
                # an isfinite on some other field doesn't witness it
                and any(
                    isinstance(a, ast.Name) and "price" in a.id
                    for arg in n.args
                    for a in ast.walk(arg)
                )
                for n in ast.walk(fn_node)
            )
            checks_budget = any(
                isinstance(node, ast.Compare)
                and any(
                    isinstance(n, ast.Name) and "iters" in n.id
                    for n in ast.walk(node)
                )
                for node in ast.walk(fn_node)
            )
            if witnesses_prices and checks_budget:
                continue
            missing_bits = []
            if not witnesses_prices:
                missing_bits.append(
                    "never witnesses the stored price-table fingerprint as finite"
                )
            if not checks_budget:
                missing_bits.append(
                    "never sanity-compares the stored iteration budget"
                )
            yield Finding(
                rule="cache-persist",
                path=f.relpath,
                line=fn_node.lineno,
                symbol=sym,
                message=(
                    "warm-dual plane restored blind: "
                    + " and ".join(missing_bits)
                    + " — restored duals would ride keys whose components "
                    "were never parsed as what the writer's contract "
                    "claims (drop the row counted, never trust it)"
                ),
                severity=SEV_ERROR,
            )
