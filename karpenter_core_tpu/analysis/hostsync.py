"""host-sync: host<->device synchronization inside device-hot modules.

The 50k-pod/<500 ms target forbids per-pod host round trips; an
accidental ``np.asarray``/``.item()`` on a device value inside
``solver/pack.py`` silently re-introduces exactly the serialization the
tensor path exists to remove. Intentional sync points (the one
``np.asarray`` after a batched dispatch) carry
``# analysis: allow-host-sync`` markers.

Detection is a linear, order-aware dataflow over each function body:
names assigned from calls to device-array-producing functions (jit-
decorated in the same module, or the configured cross-module producer
list) become *device values*; reassignment from anything else clears
them. Flagged operations:

- ``.block_until_ready()``, ``.item()``, ``.tolist()``,
  ``jax.device_get(...)`` — always (these only exist to synchronize);
- ``np.asarray / np.array / np.ascontiguousarray / float / int / bool``
  applied to an expression referencing a device value.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from .engine import FileContext, dotted_name, jit_decoration, rule
from .findings import SEV_ERROR, Finding

_ALWAYS_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NP_CONVERTERS = {"asarray", "array", "ascontiguousarray"}
_SCALAR_CASTS = {"float", "int", "bool"}


def _module_jit_functions(nodes) -> Set[str]:
    out: Set[str] = set()
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if jit_decoration(node) is not None:
                out.add(node.name)
    return out


def _callee_basename(call: ast.Call) -> str:
    name = dotted_name(call.func)
    return name.split(".")[-1] if name else ""


def _refs_any(expr: ast.AST, names: Set[str]) -> Optional[str]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in names:
            return node.id
    return None


def _assign_targets(node: ast.AST) -> List[str]:
    out: List[str] = []

    def collect(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            collect(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        collect(node.target)
    return out


class _FunctionScan:
    def __init__(self, ctx: FileContext, producers: Set[str], symbol: str):
        self.ctx = ctx
        self.producers = producers
        self.symbol = symbol
        self.device: Set[str] = set()
        self.findings: List[Finding] = []

    def flag(self, line: int, what: str) -> None:
        self.findings.append(
            Finding(
                rule="host-sync",
                path=self.ctx.relpath,
                line=line,
                symbol=self.symbol,
                message=(
                    f"{what} in device-hot module — host<->device sync; if this "
                    f"is an intentional post-dispatch sync point, mark it "
                    f"'# analysis: allow-host-sync'"
                ),
                severity=SEV_ERROR,
            )
        )

    def check_expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _ALWAYS_SYNC_METHODS:
                self.flag(node.lineno, f"'.{f.attr}()'")
                continue
            name = dotted_name(f)
            if name in ("jax.device_get",):
                self.flag(node.lineno, "'jax.device_get'")
                continue
            base = name.split(".")[-1] if name else ""
            if (
                base in _NP_CONVERTERS
                and name.split(".")[0] in ("np", "numpy")
                and node.args
            ):
                var = _refs_any(node.args[0], self.device)
                if var:
                    self.flag(node.lineno, f"'{name}' on device value '{var}'")
            elif name in _SCALAR_CASTS and node.args:
                var = _refs_any(node.args[0], self.device)
                if var:
                    self.flag(node.lineno, f"'{name}()' on device value '{var}'")

    def run_body(self, body: Iterable[ast.AST]) -> None:
        for stmt in body:
            self.run_stmt(stmt)

    def run_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested functions are scanned as their own scope
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is not None:
                self.check_expr(value)
            targets = _assign_targets(stmt)
            produced = (
                isinstance(value, ast.Call)
                and _callee_basename(value) in self.producers
            )
            for t in targets:
                self.device.discard(t)
                if produced:
                    self.device.add(t)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self.check_expr(stmt.test)
            self.run_body(stmt.body)
            self.run_body(stmt.orelse)
            return
        if isinstance(stmt, ast.For):
            self.check_expr(stmt.iter)
            self.run_body(stmt.body)
            self.run_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.check_expr(item.context_expr)
            self.run_body(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self.run_body(stmt.body)
            for h in stmt.handlers:
                self.run_body(h.body)
            self.run_body(stmt.orelse)
            self.run_body(stmt.finalbody)
            return
        if isinstance(stmt, (ast.Return, ast.Expr)) and stmt.value is not None:
            self.check_expr(stmt.value)
            return
        # default: scan any expressions hanging off the statement
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.check_expr(child)


@rule(
    "host-sync",
    "no un-annotated host<->device syncs (np.asarray/.item()/...) in device-hot modules",
)
def check_host_sync(ctx: FileContext):
    if not ctx.is_device_hot():
        return
    producers = _module_jit_functions(ctx.walk()) | set(ctx.config.device_producers)
    symbols: Dict[ast.AST, str] = {}

    def visit(node: ast.AST, sym: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # jit-decorated functions (and everything nested in them)
                # are device code — conversions inside are traced ops,
                # not host syncs
                if jit_decoration(child) is not None:
                    continue
                child_sym = f"{sym}.{child.name}" if sym else child.name
                symbols[child] = child_sym
                visit(child, child_sym)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{sym}.{child.name}" if sym else child.name)
            else:
                visit(child, sym)

    visit(ctx.tree, "")
    for fn, sym in symbols.items():
        scan = _FunctionScan(ctx, producers, sym)
        scan.run_body(fn.body)
        for f in scan.findings:
            yield f
