"""Static verification of the solver's shape contracts.

For every ``@contract``-annotated tensor function, bind each dimension
letter to a distinct prime, build abstract ``jax.ShapeDtypeStruct``
inputs, and run ``jax.eval_shape`` — the function is traced with its
real jit pipeline but no kernel executes, so the declared output shape
is checked against what XLA would actually produce, in milliseconds.
Distinct primes make accidental dimension transposition impossible to
miss (P·R == R·P but (3, 5) != (5, 3)).

This module is the only part of the analysis package that imports jax;
the AST rule engine stays stdlib-only.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

_PRIMES = [3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41]

_DTYPES = {
    None: "int32",
    "i4": "int32",
    "i8": "int64",
    "f4": "float32",
    "f8": "float64",
    "b1": "bool",
}


@dataclass
class ContractResult:
    name: str
    ok: bool
    checked: bool  # False = runtime-only contract (host/numpy fn)
    detail: str = ""


class _DimEnv:
    """letter → concrete prime size, bound on first use."""

    def __init__(self) -> None:
        self.env: Dict[str, int] = {}
        self._next = 0

    def __call__(self, letter: str) -> int:
        if letter.isdigit():
            return int(letter)
        if letter in ("*", "_"):
            v = _PRIMES[self._next % len(_PRIMES)]
            self._next += 1
            return v
        if letter not in self.env:
            self.env[letter] = _PRIMES[self._next % len(_PRIMES)]
            self._next += 1
        return self.env[letter]


def _build_input(spec: Optional[str], dtype_code: Optional[str], dims: _DimEnv):
    import jax
    import numpy as np

    from ..solver.contracts import _parse

    tokens = _parse(spec)
    if tokens is None:
        return None
    shape = tuple(dims(t) for t in tokens)
    return jax.ShapeDtypeStruct(shape, np.dtype(_DTYPES.get(dtype_code, dtype_code)))


def verify_contracts(names: Optional[List[str]] = None) -> List[ContractResult]:
    """Run eval_shape over the contract registry → per-function results."""
    import jax

    from ..solver import contracts as C

    # importing the solver modules registers their contracts
    from ..solver import encode, kernels, merge, pack  # noqa: F401

    results: List[ContractResult] = []
    for entry in C.REGISTRY:
        name = entry["name"]
        if names is not None and name not in names:
            continue
        if not entry.get("eval_shape", True):
            results.append(
                ContractResult(name, True, checked=False, detail="runtime-only (host fn)")
            )
            continue
        dims = _DimEnv()
        try:
            if entry["example"] is not None:
                args, kwargs = entry["example"](dims)
            else:
                dtypes = entry["dtypes"] or (None,) * len(entry["in_specs"])
                args = tuple(
                    _build_input(spec, dt, dims)
                    for spec, dt in zip(entry["in_specs"], dtypes)
                )
                if any(a is None for a in args):
                    results.append(
                        ContractResult(
                            name,
                            True,
                            checked=False,
                            detail="unspecced args and no example builder",
                        )
                    )
                    continue
                kwargs = dict(entry["static"])
            fn = entry["fn"]
            if kwargs:
                # eval_shape abstracts every argument; static kwargs
                # (e.g. the compat kernels' `keys` tuple) must be closed
                # over so the jit wrapper sees them as static
                import functools

                fn = functools.partial(fn, **kwargs)
            out = jax.eval_shape(fn, *args)
            C._check_out(name, entry["out"], out, dims.env)
            results.append(
                ContractResult(
                    name,
                    True,
                    checked=True,
                    detail=", ".join(f"{k}={v}" for k, v in sorted(dims.env.items())),
                )
            )
        except Exception as e:  # noqa: BLE001 — every failure becomes a report entry
            results.append(ContractResult(name, False, checked=True, detail=str(e)[:500]))
    return results
