"""General hygiene rules: silent broad excepts, mutable default args,
jax imports in host-only control-plane modules.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from .engine import FileContext, dotted_name, rule
from .findings import SEV_ERROR, SEV_WARNING, Finding

# ---------------------------------------------------------------------------
# broad-except


def _is_broad(handler: ast.ExceptHandler) -> Optional[str]:
    """'Exception'/'BaseException'/bare — else None."""
    t = handler.type
    if t is None:
        return "bare except"
    name = dotted_name(t)
    if name in ("Exception", "BaseException", "builtins.Exception"):
        return f"except {name}"
    return None


def _swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler body only passes/continues/breaks or returns
    a constant — the exception vanishes with no logging, re-raise, or
    handling of any kind."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return):
            v = stmt.value
            if v is None or isinstance(v, ast.Constant):
                continue
            if isinstance(v, (ast.List, ast.Tuple)) and not v.elts:
                continue
            if isinstance(v, ast.Dict) and not v.keys:
                continue
            return False
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


@rule(
    "broad-except",
    "'except Exception' must not swallow silently — narrow it, log it, re-raise, or mark allow-broad-except",
)
def check_broad_except(ctx: FileContext):
    from .engine import qualify

    qual = None
    for node in ctx.walk():
        if not isinstance(node, ast.ExceptHandler):
            continue
        kind = _is_broad(node)
        if kind is None or not _swallows(node):
            continue
        if qual is None:
            qual = qualify(ctx.tree)
        yield Finding(
            rule="broad-except",
            path=ctx.relpath,
            line=node.lineno,
            symbol=qual.get(node, ""),
            message=(
                f"'{kind}' swallows the exception silently (handler only "
                f"passes/continues/returns a constant) — narrow the type, log, "
                f"or re-raise"
            ),
            severity=SEV_ERROR,
        )


# ---------------------------------------------------------------------------
# mutable defaults

_MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
_MUTABLE_CALLS = {"list", "dict", "set", "defaultdict", "OrderedDict"}


@rule("mutable-default", "no mutable default arguments")
def check_mutable_default(ctx: FileContext):
    from .engine import qualify

    qual = None
    for node in ctx.walk():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        a = node.args
        pos = a.posonlyargs + a.args
        pairs = list(zip(pos[len(pos) - len(a.defaults) :], a.defaults))
        pairs += [(p, d) for p, d in zip(a.kwonlyargs, a.kw_defaults) if d is not None]
        for p, d in pairs:
            bad = isinstance(d, _MUTABLE) or (
                isinstance(d, ast.Call) and dotted_name(d.func) in _MUTABLE_CALLS
            )
            if not bad:
                continue
            if qual is None:
                qual = qualify(ctx.tree)
            name = getattr(node, "name", "<lambda>")
            yield Finding(
                rule="mutable-default",
                path=ctx.relpath,
                line=d.lineno,
                symbol=qual.get(node, name),
                message=(
                    f"parameter '{p.arg}' of '{name}' has a mutable default — "
                    f"shared across calls; use None and construct inside"
                ),
                severity=SEV_WARNING,
            )


# ---------------------------------------------------------------------------
# jnp in host-only modules


@rule(
    "jnp-host-only",
    "control-plane modules must not import jax — backend init belongs to the solver",
)
def check_jnp_host_only(ctx: FileContext):
    if not ctx.is_host_only():
        return
    for node in ctx.walk():
        mods: List[str] = []
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            mods = [node.module]
        for m in mods:
            if m == "jax" or m.startswith("jax."):
                yield Finding(
                    rule="jnp-host-only",
                    path=ctx.relpath,
                    line=node.lineno,
                    symbol="",
                    message=(
                        f"host-only module imports '{m}' — jax/backend init must "
                        f"stay behind the solver boundary (solver/backend.py)"
                    ),
                    severity=SEV_ERROR,
                )
