"""Runtime lock-order witness (ISSUE 18): the dynamic half of the
concurrency proof plane.

When installed (env-switched by tests/conftest.py via
``KARPENTER_TPU_LOCK_WITNESS=1``, BEFORE the package imports — exactly
like the shape-contract switch; off in production), the witness
monkeypatches ``threading.Lock`` / ``RLock`` / ``Condition`` with
factories that inspect the *creation site* of each primitive. A site
present in the static lock inventory (``concurrency.witness_inventory``)
with a matching constructor kind gets a thin recording wrapper; every
other creation — stdlib internals, function-local locks, sink locks —
gets the real primitive untouched.

Wrapped primitives maintain a per-thread held stack and record every
*consecutive* acquisition edge (top-of-stack lock held when another
inventoried lock is acquired). At session teardown the conftest fixture
asserts ``observed ⊆ static_order_graph()``: every nesting the test
suite actually exercised was predicted by the static analysis. The two
sides validate each other — a static resolution gap shows up as an
unexplained observed edge, and a static-only edge costs nothing (the
graph is a may-analysis superset by construction).

Sink locks (observability/interning leaves) are deliberately NOT
instrumented: a metrics bump under a Condition is statically invisible
but provably harmless — the lock-order rule verifies sinks never
acquire coordination locks, so no sink can extend a chain.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, Optional, Set, Tuple

ENV_SWITCH = "KARPENTER_TPU_LOCK_WITNESS"

# real primitives captured at import time — factories and internal
# bookkeeping must never recurse through the patch
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_installed = False
_root: str = ""
_inventory: Dict[Tuple[str, int], Tuple[str, str]] = {}
_edges: Set[Tuple[str, str]] = set()
_edges_mu = _REAL_LOCK()
_tls = threading.local()


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = []
        _tls.stack = s
    return s


def _push(lock_id: str, record: bool = True) -> None:
    s = _stack()
    if record and s and s[-1] != lock_id:
        edge = (s[-1], lock_id)
        with _edges_mu:
            _edges.add(edge)
    s.append(lock_id)


def _pop(lock_id: str) -> None:
    s = _stack()
    for i in range(len(s) - 1, -1, -1):
        if s[i] == lock_id:
            del s[i]
            return


class _WitnessLock:
    """Recording proxy over a real Lock/RLock. Only the acquisition
    protocol is intercepted; everything else delegates."""

    __slots__ = ("_lock", "lock_id")

    def __init__(self, lock, lock_id: str) -> None:
        self._lock = lock
        self.lock_id = lock_id

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            _push(self.lock_id)
        return got

    def release(self) -> None:
        _pop(self.lock_id)
        self._lock.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        fn = getattr(self._lock, "locked", None)
        return bool(fn()) if fn is not None else False

    def __repr__(self) -> str:
        return f"<witness {self.lock_id} over {self._lock!r}>"


class _WitnessCondition:
    """Recording proxy over a real Condition. ``wait`` pops the held
    entry for its duration (the wait releases the underlying lock) and
    re-pushes WITHOUT recording — the original acquisition already
    recorded the edge, and a fresh edge at wakeup would invent
    orderings the code never requested."""

    __slots__ = ("_cond", "lock_id")

    def __init__(self, cond, lock_id: str) -> None:
        object.__setattr__(self, "_cond", cond)
        object.__setattr__(self, "lock_id", lock_id)

    def acquire(self, *args, **kwargs) -> bool:
        got = self._cond.acquire(*args, **kwargs)
        if got:
            _push(self.lock_id)
        return got

    def release(self) -> None:
        _pop(self.lock_id)
        self._cond.release()

    def __enter__(self) -> bool:
        got = self._cond.__enter__()
        _push(self.lock_id)
        return got

    def __exit__(self, *exc) -> None:
        _pop(self.lock_id)
        return self._cond.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None):
        _pop(self.lock_id)
        try:
            return self._cond.wait(timeout)
        finally:
            _push(self.lock_id, record=False)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _pop(self.lock_id)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _push(self.lock_id, record=False)

    def __getattr__(self, name: str):
        return getattr(object.__getattribute__(self, "_cond"), name)

    def __repr__(self) -> str:
        return f"<witness {self.lock_id} over {self._cond!r}>"


def _site_of_caller() -> Optional[Tuple[str, int]]:
    frame = sys._getframe(2)
    fn = frame.f_code.co_filename
    if not fn.startswith(_root):
        return None
    rel = os.path.relpath(fn, _root).replace(os.sep, "/")
    return (rel, frame.f_lineno)


def _lock_factory(*args, **kwargs):
    site = _site_of_caller()
    hit = _inventory.get(site) if site is not None else None
    if hit is not None and hit[1] == "Lock":
        return _WitnessLock(_REAL_LOCK(*args, **kwargs), hit[0])
    return _REAL_LOCK(*args, **kwargs)


def _rlock_factory(*args, **kwargs):
    site = _site_of_caller()
    hit = _inventory.get(site) if site is not None else None
    # `threading.Condition(threading.RLock())` shares one creation line:
    # the inventory entry there is the Condition — kind-mismatched sites
    # get the real primitive so the Condition factory wraps exactly once
    if hit is not None and hit[1] == "RLock":
        return _WitnessLock(_REAL_RLOCK(*args, **kwargs), hit[0])
    return _REAL_RLOCK(*args, **kwargs)


def _condition_factory(*args, **kwargs):
    site = _site_of_caller()
    hit = _inventory.get(site) if site is not None else None
    if hit is not None and hit[1] == "Condition":
        return _WitnessCondition(_REAL_CONDITION(*args, **kwargs), hit[0])
    return _REAL_CONDITION(*args, **kwargs)


def install(root: Optional[str] = None) -> bool:
    """Patch the threading constructors. Idempotent; returns whether the
    witness is installed after the call. Must run BEFORE the package
    modules that create inventoried locks are imported."""
    global _installed, _root
    if _installed:
        return True
    from .concurrency import witness_inventory
    from .engine import repo_root

    _root = os.path.abspath(root or repo_root())
    _inventory.update(witness_inventory(_root))
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _installed = True
    return True


def uninstall() -> None:
    """Restore the real constructors (already-created wrappers keep
    working — they hold real primitives)."""
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _installed = False


def installed() -> bool:
    return _installed


def observed_edges() -> Set[Tuple[str, str]]:
    with _edges_mu:
        return set(_edges)


def reset_edges() -> None:
    with _edges_mu:
        _edges.clear()


def instrumented_count() -> int:
    return len(_inventory)


def verify_against_static(root: Optional[str] = None) -> Tuple[Set[Tuple[str, str]], Set[Tuple[str, str]]]:
    """→ (observed, unexplained): the witness passes when ``unexplained``
    is empty — every observed acquisition edge is in the static graph."""
    from .concurrency import static_order_graph

    observed = observed_edges()
    static = static_order_graph(root or _root or None)
    return observed, observed - static
