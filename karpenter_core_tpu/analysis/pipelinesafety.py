"""pipeline-safety: no shared mutable state across serving-stage
threads without a lock or a handoff queue (ISSUE 6).

The serving pipeline (``karpenter_core_tpu/serving/``) is the one
package where multiple long-lived threads cooperate by design. Its
concurrency discipline is explicit:

- work items cross stage boundaries only through ``StageQueue``
  (ownership transfers at put/get);
- everything else shared between threads is either immutable after
  ``__init__``, a synchronization primitive, or guarded by the owning
  class's lock/condition.

This rule enforces the discipline per class:

1. A class participates iff it spawns threads on its own methods
   (``threading.Thread(target=self.m)``) — those methods and their
   intra-class transitive callees form per-entry *thread contexts*;
   every other method (public API, watch callbacks, debug routes) is
   the *external* context.
2. A field participates iff it is MUTATED outside ``__init__``
   (assignment, ``self.x[k] = v``, or a mutating method call like
   ``.append``/``.pop``) and is accessed from two or more contexts —
   that is exactly "mutable state crossing a stage boundary".
3. Every access (read or write) to a participating field must be
   lexically under ``with self.<lock>`` (Lock/RLock/Condition), unless
   the field holds a synchronization/handoff object (constructed from
   ``StageQueue``/``queue.Queue``/``threading.Event``/...), whose own
   methods are the safe crossing.

Known under-approximation: two accesses that both fall in the
*external* context can still race each other (two foreign threads);
the rule targets the stage-crossing hazard class, which is what the
serving design must keep structurally impossible.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .engine import FileContext, dotted_name, rule
from .findings import SEV_ERROR, Finding
from .locks import _MUTATORS, _self_field_root

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock",
               "threading.Condition", "Condition"}

# constructors whose instances are themselves the legal crossing: their
# methods synchronize internally (handoff queues, events, semaphores)
_SYNC_CTOR_SUFFIXES = (
    "StageQueue",
    "Queue",
    "LifoQueue",
    "SimpleQueue",
    "Event",
    "Condition",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "Lock",
    "RLock",
    # trace-context handoff objects (ISSUE 10): a TraceContext is
    # immutable after construction and ``tracer.capture()`` returns one
    # (or None) — publishing the reference across stage threads is a
    # single GIL-atomic store of an immutable value, the tracer's
    # documented crossing discipline. Known under-approximation: any
    # ``.capture()`` call matches, not just the tracer's.
    "TraceContext",
    "capture",
)

_EXEMPT = {"__init__", "__new__"}


def _in_scope(ctx: FileContext) -> bool:
    rel = ctx.relpath
    if rel.startswith("karpenter_core_tpu/"):
        return any(
            rel.startswith(p) for p in getattr(ctx.config, "serving_prefixes", ())
        )
    return True  # fixture snippets opt in by living outside the package


def _ctor_fields(cls: ast.ClassDef, suffixes) -> Set[str]:
    """self.X fields assigned a call whose callee name ends with one of
    ``suffixes`` (anywhere in the class — re-assignment in start() of
    the same type keeps the exemption)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = dotted_name(node.value.func)
            if name and any(name.split(".")[-1] == s for s in suffixes):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        out.add(t.attr)
    return out


def _thread_entries(cls: ast.ClassDef) -> Set[str]:
    """Method names passed as Thread(target=self.<m>)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        if dotted_name(node.func).split(".")[-1] != "Thread":
            continue
        for kw in node.keywords:
            if kw.arg == "target" and isinstance(kw.value, ast.Attribute):
                v = kw.value
                if isinstance(v.value, ast.Name) and v.value.id == "self":
                    out.add(v.attr)
    return out


class _Access:
    __slots__ = ("field", "line", "locked", "write")

    def __init__(self, field: str, line: int, locked: bool, write: bool):
        self.field = field
        self.line = line
        self.locked = locked
        self.write = write


def _scan(fn: ast.AST, locks: Set[str]) -> Tuple[List[_Access], Set[str]]:
    """(field accesses with lexical lock state, self-method callees)."""
    accesses: List[_Access] = []
    callees: Set[str] = set()
    call_funcs: Set[int] = set()

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            for child in ast.iter_child_nodes(node):
                visit(child, False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquires = any(
                isinstance(i.context_expr, ast.Attribute)
                and isinstance(i.context_expr.value, ast.Name)
                and i.context_expr.value.id == "self"
                and i.context_expr.attr in locks
                for i in node.items
            )
            for item in node.items:
                visit(item, locked)
            for stmt in node.body:
                visit(stmt, locked or acquires)
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr not in locks
            and id(node) not in call_funcs
        ):
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            accesses.append(_Access(node.attr, node.lineno, locked, write))
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, (ast.Store, ast.Del)):
            root = _self_field_root(node, locks)
            if root:
                accesses.append(_Access(root, node.lineno, locked, True))
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
            ):
                callees.add(f.attr)
                call_funcs.add(id(f))
            elif isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                root = _self_field_root(f.value, locks)
                if root:
                    accesses.append(_Access(root, node.lineno, locked, True))
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    for stmt in getattr(fn, "body", ()):
        visit(stmt, False)
    return accesses, callees


@rule(
    "pipeline-safety",
    "serving-stage classes: mutable state crossing thread contexts must be "
    "lock-guarded or a handoff queue",
)
def check_pipeline_safety(ctx: FileContext):
    if not _in_scope(ctx):
        return
    for cls in ctx.walk():
        if not isinstance(cls, ast.ClassDef):
            continue
        entries = _thread_entries(cls)
        if not entries:
            continue
        locks = _ctor_fields(cls, ("Lock", "RLock", "Condition"))
        sync_fields = _ctor_fields(cls, _SYNC_CTOR_SUFFIXES)
        methods: Dict[str, Tuple[List[_Access], Set[str]]] = {}
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[item.name] = _scan(item, locks)

        # per-entry thread contexts = transitive intra-class closure
        def reach(entry: str) -> Set[str]:
            seen: Set[str] = set()
            stack = [entry]
            while stack:
                m = stack.pop()
                if m in seen or m not in methods:
                    continue
                seen.add(m)
                stack.extend(methods[m][1])
            return seen

        contexts: Dict[str, Set[str]] = {e: reach(e) for e in entries if e in methods}
        in_thread = set().union(*contexts.values()) if contexts else set()
        # the external context: public API, callbacks, debug routes —
        # anything not exclusively a thread-entry internals. A public
        # method reachable from an entry lives in BOTH contexts.
        field_ctx: Dict[str, Set[str]] = {}
        field_written: Set[str] = set()
        for name, (accesses, _callees) in methods.items():
            mctx: Set[str] = {e for e, r in contexts.items() if name in r}
            if not name.startswith("_") or name not in in_thread:
                mctx.add("external")
            if name in _EXEMPT:
                continue
            for a in accesses:
                field_ctx.setdefault(a.field, set()).update(mctx)
                if a.write:
                    field_written.add(a.field)
        shared = {
            f
            for f, ctxs in field_ctx.items()
            if len(ctxs) > 1 and f in field_written and f not in sync_fields
        }
        if not shared:
            continue
        lock_name = sorted(locks)[0] if locks else "<lock>"
        for name, (accesses, _callees) in methods.items():
            if name in _EXEMPT:
                continue
            seen: Set[Tuple[str, int]] = set()
            for a in accesses:
                if a.locked or a.field not in shared:
                    continue
                key = (a.field, a.line)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    rule="pipeline-safety",
                    path=ctx.relpath,
                    line=a.line,
                    symbol=f"{cls.name}.{name}",
                    message=(
                        f"field '{a.field}' is mutable state shared across "
                        f"stage-thread contexts ({', '.join(sorted(field_ctx[a.field]))}) "
                        f"— access it under 'self.{lock_name}' or hand it off "
                        f"through a StageQueue"
                    ),
                    severity=SEV_ERROR,
                )
