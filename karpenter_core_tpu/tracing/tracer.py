"""Low-overhead span tracer for the solve pipeline.

Design constraints (the tracer instruments a path whose whole budget is
<500 ms for 50k pods, ISSUE 1):

- ``span()`` is a no-op costing one thread-local read when no trace is
  active on the calling thread, so library code (devicetime, pack,
  topology seeding) can be instrumented unconditionally.
- All timestamps come from ``time.perf_counter_ns()`` — one monotonic
  clock for every span, so durations nest exactly and the exported
  trace is internally consistent (wall time is recorded once per trace
  for file naming / correlation only).
- Spans carry a parent reference and accumulate child time, so
  *self time* (duration minus direct children) is exact without a
  post-hoc interval scan; the sum of self times over a trace equals
  the root duration, which is what lets ``bench.py`` emit a
  ``phase_breakdown_ms`` that reconciles with ``host_ms + device_ms``.
- Completed traces land in a fixed-capacity ring buffer (newest-wins)
  read by the ``/debug/traces`` routes; nothing is retained beyond it
  unless the slow-solve capture persists a copy.
- The system is concurrent (serving stage threads, fleet worker lanes,
  the prewarm double buffer), but the tracer stays thread-local: a
  worker thread joins a decision's trace only by *adopting* an explicit
  ``TraceContext`` captured where the work was enqueued
  (``capture()``/``adopt()``). Adopted spans land on their own thread
  lane of the shared trace; they are linked children of the capture
  point but never subtract from its self time (concurrent time is not
  nested time), so the root lane's self times still partition the root
  span exactly. Spans born on a thread with no active root and no
  adopted context are *orphans*: they vanish from every trace, which is
  an attribution bug — they are counted
  (``karpenter_tpu_tracer_orphan_spans_total`` via the metrics bridge)
  so the serving/fleet identity tests can assert the count stays zero.

Cross-thread mutation discipline: a ``Trace`` is deliberately lock-free.
Every mutation reachable from an adopted (foreign-thread) context is a
single GIL-atomic operation — ``spans.append``, ``links.append``,
``args[k] = v``, ``contains_solve = True`` — and ``parent.child_ns``
accumulation only ever happens between spans on the SAME thread's
stack. Readers (/debug routes, the flight recorder) consume traces
after the root finished, or tolerate a momentarily-short span list.

The metrics bridge: a trace may carry a histogram sink (the scheduler's
``solver_phase_duration``); every completed span is observed under
``phase=<span name>``, which keeps the pre-existing coarse labels
(existing_pack / encode / pack / affinity_postpass) and adds the
fine-grained ones (encode.compat_wait, pack.dispatch, ...). The bridge
runs even when recording is disabled (KARPENTER_TPU_TRACE=0) so the
metric surface never depends on the tracing knob.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

# synthetic-lane thread id: spans on this lane (e.g. the per-solve
# device_total rollup) are derived quantities, not measured host spans —
# excluded from phase breakdowns, exported on their own named track
SYNTHETIC_TID = -1

_trace_counter = itertools.count(1)

# -- orphan-span accounting (ISSUE 10 satellite) ----------------------------
# A span on a thread with no active trace used to vanish silently; with
# cross-thread context propagation in place that is always an attribution
# bug, so it is counted. The counter is process-global (the metrics
# registry bridges it into karpenter_tpu_tracer_orphan_spans_total) and
# resettable so tests can assert "this scenario orphaned nothing".
_orphan_mu = threading.Lock()
_orphan_total = 0
_orphan_recent: List[str] = []  # last few orphaned span names (debugging)
_ORPHAN_RECENT_KEEP = 16


def _count_orphan(name: str) -> None:
    global _orphan_total
    with _orphan_mu:
        _orphan_total += 1
        _orphan_recent.append(name)
        del _orphan_recent[:-_ORPHAN_RECENT_KEEP]


def orphan_spans() -> int:
    """Spans dropped because no trace was active on their thread."""
    with _orphan_mu:
        return _orphan_total


def orphan_recent() -> List[str]:
    """Names of the most recently orphaned spans (newest last)."""
    with _orphan_mu:
        return list(_orphan_recent)


def reset_orphans() -> None:
    global _orphan_total
    with _orphan_mu:
        _orphan_total = 0
        _orphan_recent.clear()


def enabled() -> bool:
    """Span *recording* switch (env, read per trace so tests and the
    bench overhead comparison can flip it without reimporting). The
    metrics bridge is unaffected — see module docstring."""
    return os.environ.get("KARPENTER_TPU_TRACE", "1") != "0"


class Span:
    """One timed region. ``ts_ns``/``dur_ns`` are perf_counter_ns
    values; ``parent`` is the enclosing Span (None for the root);
    ``child_ns`` accumulates direct children's durations so
    ``self_ns`` needs no interval arithmetic."""

    __slots__ = ("name", "ts_ns", "dur_ns", "tid", "depth", "parent", "child_ns", "args")

    def __init__(self, name: str, ts_ns: int, tid: int, depth: int, parent: Optional["Span"], args: Optional[dict]):
        self.name = name
        self.ts_ns = ts_ns
        self.dur_ns = 0
        self.tid = tid
        self.depth = depth
        self.parent = parent
        self.child_ns = 0
        self.args = args

    @property
    def self_ns(self) -> int:
        return self.dur_ns - self.child_ns

    def __repr__(self) -> str:  # debugging aid only
        return f"Span({self.name!r}, dur={self.dur_ns / 1e6:.3f}ms, depth={self.depth})"


class Trace:
    """One traced operation (normally one solve): a ``trace_id``, the
    completed spans, and optional sinks (metrics histogram)."""

    __slots__ = (
        "trace_id",
        "name",
        "start_ns",
        "end_ns",
        "wall_start",
        "pid",
        "spans",
        "metrics_sink",
        "record",
        "contains_solve",
        "args",
        "root_tid",
        "links",
    )

    def __init__(self, name: str, trace_id: Optional[str] = None, metrics_sink=None, record: bool = True, **args):
        if trace_id is None:
            trace_id = f"t{next(_trace_counter):06d}-{os.getpid():x}"
        self.trace_id = trace_id
        self.name = name
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.wall_start = time.time()
        self.pid = os.getpid()
        self.spans: List[Span] = []
        self.metrics_sink = metrics_sink
        self.record = record
        self.contains_solve = False
        self.args = dict(args)
        # thread the root span runs on: the authoritative lane whose
        # self times partition the root duration. None until trace_root
        # installs the trace (directly-constructed Traces keep the
        # pre-adoption behavior: every lane counts).
        self.root_tid: Optional[int] = None
        # trace_ids of related traces/contexts (e.g. the N tenant solves
        # coalesced into one mega-dispatch) — appended GIL-atomically
        self.links: List[dict] = []

    def add_link(self, trace_id: str, **meta) -> None:
        """Record a relation to another trace (batched work serving many
        decisions, a probe serving a foreign decision, ...)."""
        self.links.append({"trace_id": trace_id, **meta})

    # -- accounting ---------------------------------------------------------

    @property
    def total_ms(self) -> float:
        end = self.end_ns if self.end_ns is not None else time.perf_counter_ns()
        return (end - self.start_ns) / 1e6

    def add_synthetic(self, name: str, ts_ns: int, dur_ns: int, **args) -> Span:
        """A derived span (e.g. accumulated device-attributable time) on
        the synthetic lane — exported, excluded from breakdowns."""
        s = Span(name, ts_ns, SYNTHETIC_TID, 0, None, args or None)
        s.dur_ns = max(int(dur_ns), 0)
        if self.record:
            self.spans.append(s)
        return s

    def phase_breakdown_ms(self) -> Dict[str, float]:
        """Self-time per span name on the ROOT lane, in ms. Synthetic
        spans and adopted foreign-thread lanes are excluded, so the
        values sum to the root span's duration (≈ host + device wall
        time: device waits are real measured spans; concurrent lanes
        overlap the root and would double-count)."""
        out: Dict[str, float] = {}
        root_tid = self.root_tid
        for s in self.spans:
            if s.tid == SYNTHETIC_TID:
                continue
            if root_tid is not None and s.tid != root_tid:
                continue
            out[s.name] = out.get(s.name, 0.0) + s.self_ns / 1e6
        return out

    def lane_breakdown_ms(self) -> Dict[int, Dict[str, float]]:
        """Per-thread-lane self-time breakdowns (the flight recorder's
        concurrent-lane attribution). Keys are thread idents; the root
        lane is present under ``root_tid``; synthetic spans excluded."""
        out: Dict[int, Dict[str, float]] = {}
        for s in self.spans:
            if s.tid == SYNTHETIC_TID:
                continue
            lane = out.setdefault(s.tid, {})
            lane[s.name] = lane.get(s.name, 0.0) + s.self_ns / 1e6
        return out

    def device_ms(self) -> float:
        """Sum of measured device-wait span durations."""
        return sum(s.dur_ns for s in self.spans if s.name == "device_wait") / 1e6


class TraceRing:
    """Fixed-capacity newest-wins buffer of completed traces."""

    def __init__(self, capacity: int = 32):
        self._mu = threading.Lock()
        self._capacity = max(1, int(capacity))
        self._items: List[Trace] = []

    @property
    def capacity(self) -> int:
        with self._mu:
            return self._capacity

    def set_capacity(self, capacity: int) -> None:
        with self._mu:
            self._capacity = max(1, int(capacity))
            del self._items[: -self._capacity]

    def push(self, trace: Trace) -> None:
        with self._mu:
            self._items.append(trace)
            if len(self._items) > self._capacity:
                del self._items[: -self._capacity]

    def last(self) -> Optional[Trace]:
        with self._mu:
            return self._items[-1] if self._items else None

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._mu:
            for t in reversed(self._items):
                if t.trace_id == trace_id:
                    return t
        return None

    def all(self) -> List[Trace]:
        with self._mu:
            return list(self._items)

    def clear(self) -> None:
        with self._mu:
            self._items.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._items)


try:
    _RING_CAP = max(1, int(os.environ.get("KARPENTER_TPU_TRACE_BUFFER", "32")))
except ValueError:
    _RING_CAP = 32
RING = TraceRing(_RING_CAP)

_tls = threading.local()

# sentinel trace installed while recording is disabled (KARPENTER_TPU_TRACE=0
# with no metrics sink): inner spans must neither record nor count as
# orphans — the whole subtree is deliberately off, not lost. record=False
# keeps span() from appending to it; the object is shared process-wide and
# never buffered.
_DISABLED = Trace("disabled", trace_id="disabled", record=False)


def current_trace() -> Optional[Trace]:
    tr = getattr(_tls, "trace", None)
    return None if tr is _DISABLED else tr


def current_trace_id() -> Optional[str]:
    tr = getattr(_tls, "trace", None)
    return tr.trace_id if tr is not None and tr is not _DISABLED else None


class TraceContext:
    """An explicit handoff of 'where this work belongs': the active
    trace and the innermost open span at capture time. Immutable — the
    one legal way a trace crosses a thread boundary (queue items, the
    prewarm handshake, fleet lane submissions carry one; the consuming
    thread re-enters the trace with ``adopt``)."""

    __slots__ = ("trace", "parent")

    def __init__(self, trace: Trace, parent: Optional[Span]):
        self.trace = trace
        self.parent = parent

    @property
    def trace_id(self) -> str:
        return self.trace.trace_id

    def __repr__(self) -> str:  # debugging aid only
        return f"TraceContext({self.trace_id!r}, parent={self.parent and self.parent.name!r})"


def capture() -> Optional[TraceContext]:
    """Snapshot the calling thread's active trace + innermost span for
    re-adoption on another thread. None when nothing is being traced
    (the consumer's ``adopt`` then degrades to a no-op)."""
    tr = getattr(_tls, "trace", None)
    if tr is None or tr is _DISABLED:
        return None
    stack: List[Span] = getattr(_tls, "stack", [])
    return TraceContext(tr, stack[-1] if stack else None)


@contextmanager
def adopt(ctx: Optional[TraceContext], name: str, **args):
    """Re-enter a captured trace on the consuming thread.

    The adopted region opens one anchor span (``name``) parented at the
    capture point and runs on this thread's own lane of the shared
    trace; nested ``span()``/``trace_root()`` calls inside join it
    normally. The anchor's duration is NOT added to the capture-point
    parent's child time — the lanes run concurrently, so adopted time
    must not eat the root lane's self time.

    Degrades safely: ``ctx`` None → pass-through (yields None); the
    captured trace already active on this thread → a plain nested span;
    a DIFFERENT trace active here → a span on the active trace carrying
    the foreign trace_id as a link (a thread cannot serve two traces,
    but the relation is recorded on both)."""
    if ctx is None:
        yield None
        return
    tr = getattr(_tls, "trace", None)
    if tr is not None and tr is not _DISABLED:
        if tr is ctx.trace:
            with span(name, **args) as s:
                yield s
            return
        # cross-trace: record the relation both ways, stay on the
        # thread's own trace (batched work serving many decisions)
        tr.add_link(ctx.trace_id, via=name)
        ctx.trace.add_link(tr.trace_id, via=name)
        with span(name, link=ctx.trace_id, **args) as s:
            yield s
        return
    prev = tr  # None or _DISABLED: both restored verbatim on exit
    target = ctx.trace
    anchor = Span(
        name,
        time.perf_counter_ns(),
        threading.get_ident(),
        (ctx.parent.depth + 1) if ctx.parent is not None else 0,
        ctx.parent,
        args or None,
    )
    _tls.trace = target
    _tls.stack = [anchor]
    try:
        yield anchor
    finally:
        anchor.dur_ns = time.perf_counter_ns() - anchor.ts_ns
        # concurrent lane: linked to ctx.parent for tree reconstruction,
        # deliberately absent from its child_ns (see docstring)
        if target.record:
            target.spans.append(anchor)
        sink = target.metrics_sink
        if sink is not None:
            sink.observe(anchor.dur_ns / 1e9, phase=name)
        _tls.trace = prev
        _tls.stack = []


@contextmanager
def span(name: str, **args):
    """Time a region of the active trace. No active trace on this
    thread → pass-through, but counted as an orphan (with context
    propagation in place, a span that vanishes is an attribution bug —
    see module docstring)."""
    tr = getattr(_tls, "trace", None)
    if tr is None:
        _count_orphan(name)
        yield None
        return
    if tr is _DISABLED:
        yield None
        return
    stack: List[Span] = _tls.stack
    parent = stack[-1] if stack else None
    s = Span(name, time.perf_counter_ns(), threading.get_ident(), len(stack), parent, args or None)
    stack.append(s)
    try:
        yield s
    finally:
        s.dur_ns = time.perf_counter_ns() - s.ts_ns
        stack.pop()
        if parent is not None:
            parent.child_ns += s.dur_ns
        if tr.record:
            tr.spans.append(s)
        sink = tr.metrics_sink
        if sink is not None:
            sink.observe(s.dur_ns / 1e9, phase=name)


@contextmanager
def trace_root(
    name: str,
    metrics_sink=None,
    buffer_if: str = "always",
    is_solve: bool = False,
    **args,
):
    """Open a trace on this thread (or join the active one).

    With an active trace this degrades to a plain ``span`` — the solver
    joins a provisioner-opened trace instead of starting its own — and
    attaches ``metrics_sink`` if the outer trace has none (the
    provisioner opens the trace before it knows which scheduler runs).

    ``buffer_if``: "always" pushes the finished trace to the ring;
    "solve" pushes only when a solve span ran inside it (keeps
    empty provisioner reconciles from evicting real solve traces);
    "never" suppresses buffering and capture (shadow/simulation
    solves that must not displace the live traffic's traces).
    On finish the slow-solve capture (capture.py) sees every
    buffered trace.
    """
    prev = getattr(_tls, "trace", None)
    if prev is not None and prev is not _DISABLED:
        tr = prev
        if metrics_sink is not None and tr.metrics_sink is None:
            tr.metrics_sink = metrics_sink
        if is_solve:
            tr.contains_solve = True
        with span(name, **args):
            yield tr
        return

    record = enabled()
    if not record and metrics_sink is None:
        # nothing to record and nothing to observe: park the disabled
        # sentinel so inner spans are cheap pass-throughs instead of
        # counted orphans (one env read per solve — the disabled mode
        # stays genuinely free)
        _tls.trace = _DISABLED
        _tls.stack = []
        try:
            yield None
        finally:
            _tls.trace = prev
            _tls.stack = []
        return

    tr = Trace(name, metrics_sink=metrics_sink, record=record, **args)
    tr.contains_solve = is_solve
    tr.root_tid = threading.get_ident()
    _tls.trace = tr
    _tls.stack = []
    root = Span(name, tr.start_ns, tr.root_tid, 0, None, args or None)
    _tls.stack.append(root)
    try:
        yield tr
    finally:
        root.dur_ns = time.perf_counter_ns() - root.ts_ns
        tr.end_ns = root.ts_ns + root.dur_ns
        if tr.record:
            tr.spans.append(root)
        sink = tr.metrics_sink
        if sink is not None:
            sink.observe(root.dur_ns / 1e9, phase=name)
        _tls.trace = prev
        _tls.stack = []
        if tr.record and (
            buffer_if == "always" or (buffer_if == "solve" and tr.contains_solve)
        ):
            RING.push(tr)
            from .capture import maybe_capture

            maybe_capture(tr)
