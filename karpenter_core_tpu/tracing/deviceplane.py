"""Device-plane observatory (ISSUE 16 tentpole): compile/recompile
attribution, transfer accounting, and HBM telemetry for every solve.

All telemetry before this module was host-side (PR 1 tracing, PR 10
flight recorder): XLA compilation, H2D/D2H transfer volume, and
device-memory behavior were invisible — exactly why the Pallas tile
budget was calibrated blind and the warmstore still pays full
per-process compiles after restore (ROADMAP item 2). This module makes
them first-class, per-decision observables:

- **jit-signature registry.** Every jit/shard_map entry point in the
  solver hot path registers through ``wrap()`` (or the ``observe_jit``
  decorator form). The wrapper records, per function, the population of
  abstract call signatures — array args as ``(shape, dtype)``, the rest
  as static-config reprs — with call counts and the wall time of each
  signature's first call (the compile-bearing call: jax caches
  executables per abstract signature, so a signature's first arrival IS
  the compile). The registry is what ROADMAP item 2's
  ``warmup_compile_only`` prewarmer will replay; it persists through
  the warmstore snapshot as the ``jitsig`` inventory plane.
- **recompile attribution.** A new signature raises a compile event with
  a cause (``first`` — the function's first signature ever,
  ``new_shape`` — the abstract array shapes changed, ``new_config`` —
  shapes match a known signature but the static config differs) and the
  triggering solve's trace_id, which rides the event as the exemplar on
  ``karpenter_tpu_xla_compiles_total{fn,cause}`` (exemplars are served
  through ``/debug/device`` and the stats ``device`` block — the classic
  text exposition stays exemplar-free, like the histogram exemplars).
- **transfer accounting.** ``record_transfer(direction, nbytes, phase)``
  rides the ``devicetime.track(phase=...)`` seam: every tracked device
  boundary reports the bytes it moved, split H2D/D2H per solve phase
  (``karpenter_tpu_solver_transfer_bytes_total{direction,phase}``).
- **HBM telemetry.** The solver polls device memory watermarks at solve
  end (``devicetime.device_memory_stats`` — this module must stay
  jax-free, it lives in the host-only tracing tier) and pairs them with
  the padded-buffer footprint estimate the kernels report
  (``record_footprint``), compared against the
  ``KARPENTER_TPU_COMPAT_TILE_MB`` budget so tile headroom is a number
  instead of a guess.

Per-solve attribution follows the sharding pad-stats pattern: the
solver calls ``reset_solve()`` at solve entry and drains
``consume_solve()`` in the solve's finally block into
``solver.last_device_stats`` → stats.py SCHEMA=5 ``device`` block →
flight recorder / bench ``_split`` / ledger. Process-global totals
(``compile_count()``, ``totals()``, ``debug_state()``) back the bench
zero-recompile gates and the ``/debug/device`` route.

Knob: ``KARPENTER_TPU_DEVICEPLANE=0`` disables everything — wrapped
functions dispatch straight through, reset/consume are no-ops.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

CAUSE_FIRST = "first"
CAUSE_NEW_SHAPE = "new_shape"
CAUSE_NEW_CONFIG = "new_config"
# a compile raised inside prewarm_scope(): the jitsig replay paying (or
# cache-hitting) the predicted compile at boot, before any solve — never
# appended to the per-solve accumulator, counted in its own total
CAUSE_PREWARM_REPLAY = "prewarm_replay"

# newest-wins ring of compile events for /debug/device exemplars
_EVENTS_KEEP = 256
# per-function signature population cap: the registry is an inventory,
# not a cache — a function cycling through unbounded shapes is itself
# the pathology the compile counter surfaces, so cap the roster and
# count what fell off instead of growing without bound
_SIGS_PER_FN = 512


def enabled() -> bool:
    return os.environ.get("KARPENTER_TPU_DEVICEPLANE", "1") != "0"


# ---------------------------------------------------------------------------
# process-global registry


class _FnRecord:
    """One registered jit entry point: its signature population and
    compile history."""

    __slots__ = (
        "name", "call_site", "static_names", "signatures",
        "calls", "compiles", "evicted", "wrapper",
    )

    def __init__(self, name: str, call_site: str, static_names: Tuple[str, ...]):
        self.name = name
        self.call_site = call_site
        self.static_names = tuple(static_names)
        # sig key -> {"count", "first_ms", "restored"}
        self.signatures: "OrderedDict[tuple, dict]" = OrderedDict()
        self.calls = 0
        self.compiles = 0
        self.evicted = 0
        # the latest observing wrapper registered under this name — the
        # jitsig-replay prewarmer calls signatures back through it so
        # replay bookkeeping rides the same seam as live traffic
        self.wrapper: Optional[Callable] = None


_MU = threading.Lock()
_REGISTRY: Dict[str, _FnRecord] = {}
_EVENTS: deque = deque(maxlen=_EVENTS_KEEP)
_TOTALS = {"compiles": 0, "calls": 0, "prewarm_compiles": 0}
# process-global transfer totals (per-solve splits live on the TLS acc)
_TRANSFERS: Dict[Tuple[str, str], int] = {}

_tls = threading.local()


def _acc() -> Optional[dict]:
    return getattr(_tls, "acc", None)


def in_prewarm() -> bool:
    return bool(getattr(_tls, "prewarm", False))


@contextlib.contextmanager
def prewarm_scope():
    """Mark this thread as replaying the jitsig inventory: compiles
    raised inside the scope are attributed ``cause=prewarm_replay``,
    counted in the process-global ``prewarm_compiles`` total and the
    yielded event list — never in the solve-attributed counters or the
    per-solve accumulator, so the replay cannot pollute the bench
    zero-compile gates it exists to satisfy."""
    events: List[dict] = []
    prev = getattr(_tls, "prewarm", False)
    prev_events = getattr(_tls, "prewarm_events", None)
    _tls.prewarm = True
    _tls.prewarm_events = events
    try:
        yield events
    finally:
        _tls.prewarm = prev
        _tls.prewarm_events = prev_events


def reset_solve() -> None:
    """Arm per-solve accumulation on this thread (solve entry)."""
    if not enabled():
        _tls.acc = None
        return
    _tls.acc = {
        "compiles": [],  # compile-event dicts, in order
        "transfers": {},  # (direction, phase) -> bytes
        "footprint": 0,  # max padded-buffer estimate seen this solve
    }


def consume_solve(memory: Optional[dict] = None) -> Optional[dict]:
    """Drain this thread's per-solve accumulator into the stats-shaped
    ``device`` block (None when the plane is disabled). ``memory`` is
    the solver-tier HBM poll (``devicetime.device_memory_stats()``)."""
    acc = _acc()
    _tls.acc = None
    if acc is None:
        return None
    by_phase: Dict[str, Dict[str, int]] = {}
    direction_totals = {"h2d": 0, "d2h": 0}
    for (direction, phase), nbytes in acc["transfers"].items():
        by_phase.setdefault(phase, {})[direction] = (
            by_phase.get(phase, {}).get(direction, 0) + nbytes
        )
        direction_totals[direction] = direction_totals.get(direction, 0) + nbytes
    budget_mb = tile_budget_mb()
    footprint = int(acc["footprint"])
    headroom = None
    if budget_mb > 0:
        headroom = round(1.0 - footprint / (budget_mb * 1024 * 1024), 4)
    events = acc["compiles"]
    return {
        "compiles": len(events),
        "compile_events": [dict(e) for e in events[:8]],
        "transfer_bytes": direction_totals,
        "transfer_by_phase": by_phase,
        "footprint_bytes": footprint,
        "tile_budget_mb": budget_mb,
        "tile_headroom_frac": headroom,
        "hbm": dict(memory) if memory else None,
    }


def tile_budget_mb() -> float:
    try:
        return float(os.environ.get("KARPENTER_TPU_COMPAT_TILE_MB", "64"))
    except ValueError:
        return 64.0


# ---------------------------------------------------------------------------
# the registering-jit seam


def _abstract(a: Any) -> tuple:
    """One argument's abstract type: array-likes (anything with .shape
    and .dtype — numpy or jax, traced or concrete) become
    ``("a", shape, dtype)``; dict/tuple pytrees recurse; everything else
    is static config by bounded repr. jax's executable cache keys on
    exactly this abstraction, so key equality here ⇔ cache hit there."""
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None and dtype is not None:
        return ("a", tuple(shape), str(dtype))
    if isinstance(a, dict):
        return ("d",) + tuple((k, _abstract(v)) for k, v in sorted(a.items()))
    if isinstance(a, (tuple, list)):
        return ("t",) + tuple(_abstract(v) for v in a)
    # the repr bound keeps the registry an inventory, not a heap dump —
    # but it must stay generous enough that typical static configs
    # (key tuples, small frozen dicts) survive round-trippable via
    # ast.literal_eval, or the prewarmer cannot resynthesize them; a
    # truncated row is counted skipped by the replay, never guessed at
    r = repr(a)
    return ("s", r if len(r) <= 512 else r[:509] + "...")


def _has_array(node: tuple) -> bool:
    if node[0] == "a":
        return True
    if node[0] in ("d", "t"):
        rest = node[1:]
        return any(_has_array(v if node[0] == "t" else v[1]) for v in rest)
    return False


def _sig_key(static_names: Tuple[str, ...], args: tuple, kwargs: dict) -> Tuple[tuple, tuple]:
    """(array part, static part) of one call's abstract signature: args
    whose pytree carries arrays land in the array part (shape/dtype
    population), the rest — plus anything named in ``static_names`` —
    is static config."""
    arr: List[tuple] = []
    static: List[tuple] = []
    for i, a in enumerate(args):
        node = _abstract(a)
        (arr if _has_array(node) else static).append((i, node))
    for k in sorted(kwargs):
        node = _abstract(kwargs[k])
        if k not in static_names and _has_array(node):
            arr.append((k, node))
        else:
            static.append((k, node))
    return tuple(arr), tuple(static)


def _classify(rec: _FnRecord, arr_part: tuple, static_part: tuple) -> str:
    if not rec.signatures:
        return CAUSE_FIRST
    for (known_arr, known_static), meta in rec.signatures.items():
        if meta.get("restored"):
            continue  # a restored inventory row is a prediction, not a witnessed compile
        if known_arr == arr_part and known_static != static_part:
            return CAUSE_NEW_CONFIG
    return CAUSE_NEW_SHAPE


def _record_compile(rec: _FnRecord, cause: str, ms: float, sig: tuple) -> dict:
    from .tracer import current_trace_id

    prewarm = cause == CAUSE_PREWARM_REPLAY
    event = {
        "fn": rec.name,
        "cause": cause,
        "ms": round(ms, 3),
        "trace_id": current_trace_id(),
        "wall": time.time(),
    }
    with _MU:
        if prewarm:
            _TOTALS["prewarm_compiles"] += 1
        else:
            rec.compiles += 1
            _TOTALS["compiles"] += 1
        _EVENTS.append(dict(event))
    if prewarm:
        bucket = getattr(_tls, "prewarm_events", None)
        if bucket is not None:
            bucket.append(event)
    else:
        acc = _acc()
        if acc is not None:
            acc["compiles"].append(event)
    return event


def wrap(name: str, fn: Callable, static_names: Tuple[str, ...] = (), call_site: str = "") -> Callable:
    """Register ``fn`` (an already-jitted callable) under ``name`` and
    return the observing wrapper. Signature bookkeeping is skipped
    entirely while the plane is disabled — the wrapper is then one env
    lookup + a passthrough call."""
    static_names = tuple(static_names)
    if not call_site:
        code = getattr(fn, "__wrapped__", fn)
        code = getattr(code, "__code__", None)
        if code is not None:
            call_site = f"{os.path.basename(code.co_filename)}:{code.co_firstlineno}"
    with _MU:
        rec = _REGISTRY.get(name)
        if rec is None:
            rec = _FnRecord(name, call_site, static_names)
            _REGISTRY[name] = rec

    @functools.wraps(fn)
    def observed(*args, **kwargs):
        if not enabled():
            return fn(*args, **kwargs)
        prewarm = in_prewarm()
        key = _sig_key(static_names, args, kwargs)
        with _MU:
            meta = rec.signatures.get(key)
            rec.calls += 1
            _TOTALS["calls"] += 1
            fresh = meta is None
            if fresh:
                cause = (
                    CAUSE_PREWARM_REPLAY
                    if prewarm
                    else _classify(rec, key[0], key[1])
                )
                meta = {"count": 0, "first_ms": None}
                rec.signatures[key] = meta
                while len(rec.signatures) > _SIGS_PER_FN:
                    rec.signatures.popitem(last=False)
                    rec.evicted += 1
            restored = bool(meta.pop("restored", False)) if not fresh else False
        if fresh or restored:
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            ms = (time.perf_counter() - t0) * 1e3
            with _MU:
                meta["count"] += 1
                if meta["first_ms"] is None:
                    meta["first_ms"] = round(ms, 3)
            if fresh:
                # a prewarmed (restored) signature's first live call is
                # the replayed compile the inventory predicted — counted
                # as a call, never as a recompile event
                _record_compile(rec, cause, ms, key)
            elif prewarm:
                # the prewarmer replaying a restored inventory row: the
                # predicted compile is paid (or cache-hit) here, before
                # any solve, attributed under its own cause
                _record_compile(rec, CAUSE_PREWARM_REPLAY, ms, key)
            return out
        with _MU:
            meta["count"] += 1
        return fn(*args, **kwargs)

    observed.__deviceplane_fn__ = name
    with _MU:
        rec.wrapper = observed
    return observed


def observe_jit(name: str, static_names: Tuple[str, ...] = ()):
    """Decorator form of ``wrap`` for def-site jits: stacks above the
    literal ``@jax.jit`` decoration (which stays visible to the
    host-sync / tracer-safety AST passes)."""

    def deco(fn: Callable) -> Callable:
        return wrap(name, fn, static_names=static_names)

    return deco


# ---------------------------------------------------------------------------
# transfer + footprint accounting (rides devicetime.track)


def record_transfer(direction: str, nbytes: int, phase: str = "solve") -> None:
    """Account ``nbytes`` moved across the host/device boundary.
    ``direction`` is ``h2d`` or ``d2h``; ``phase`` names the solve phase
    the move belongs to (pack, shard, lp, screen, ...)."""
    if nbytes <= 0 or not enabled():
        return
    key = (direction, phase)
    with _MU:
        _TRANSFERS[key] = _TRANSFERS.get(key, 0) + int(nbytes)
    acc = _acc()
    if acc is not None:
        acc["transfers"][key] = acc["transfers"].get(key, 0) + int(nbytes)


def nbytes_of(*arrays: Any) -> int:
    """Total byte size of array-likes (numpy or jax; anything exposing
    ``nbytes``, else size*itemsize, else 0). Duck-typed — no jax import."""
    total = 0
    for a in arrays:
        if a is None:
            continue
        n = getattr(a, "nbytes", None)
        if n is None:
            size = getattr(a, "size", None)
            itemsize = getattr(getattr(a, "dtype", None), "itemsize", None)
            n = size * itemsize if size is not None and itemsize is not None else 0
        total += int(n)
    return total


def record_footprint(nbytes: int) -> None:
    """Report one padded device-buffer footprint estimate (the budgeted
    transient — e.g. a Pallas compat tile or a shard pad block). The
    per-solve block keeps the max."""
    if nbytes <= 0 or not enabled():
        return
    acc = _acc()
    if acc is not None and nbytes > acc["footprint"]:
        acc["footprint"] = int(nbytes)


# ---------------------------------------------------------------------------
# global consumers: bench gates, /debug/device, warmstore plane


def compile_count() -> int:
    """Process-lifetime compile-event count — the bench zero-recompile
    gates snapshot this around steady loops."""
    with _MU:
        return _TOTALS["compiles"]


def totals() -> dict:
    with _MU:
        return {
            "compiles": _TOTALS["compiles"],
            "prewarm_compiles": _TOTALS["prewarm_compiles"],
            "calls": _TOTALS["calls"],
            "functions": len(_REGISTRY),
            "transfer_bytes": {f"{d}.{p}": n for (d, p), n in sorted(_TRANSFERS.items())},
        }


def prewarm_compile_count() -> int:
    """Process-lifetime prewarm-replay compile count — disjoint from
    ``compile_count()`` by construction."""
    with _MU:
        return _TOTALS["prewarm_compiles"]


def compile_totals_by_label() -> Dict[Tuple[str, str], int]:
    """(fn, cause) -> count over the retained event ring + registry
    compile counters; the metrics push uses per-solve events instead,
    this backs /debug/device."""
    out: Dict[Tuple[str, str], int] = {}
    with _MU:
        for ev in _EVENTS:
            key = (ev["fn"], ev["cause"])
            out[key] = out.get(key, 0) + 1
    return out


def recent_compiles(tail: int = 32) -> List[dict]:
    with _MU:
        return [dict(e) for e in list(_EVENTS)[-max(1, tail):]]


def _jsonable(node: Any):
    if isinstance(node, tuple):
        return [_jsonable(v) for v in node]
    return node


def registry_state() -> List[dict]:
    """Per-function inventory for /debug/device and profile_solve
    --device: signatures with shapes, call counts, first-call (compile)
    wall ms."""
    out: List[dict] = []
    with _MU:
        for rec in _REGISTRY.values():
            sigs = []
            for (arr, static), meta in rec.signatures.items():
                sigs.append(
                    {
                        "shapes": _jsonable(arr),
                        "static": _jsonable(static),
                        "count": meta.get("count", 0),
                        "first_ms": meta.get("first_ms"),
                        "restored": bool(meta.get("restored", False)),
                    }
                )
            out.append(
                {
                    "fn": rec.name,
                    "call_site": rec.call_site,
                    "static_names": list(rec.static_names),
                    "calls": rec.calls,
                    "compiles": rec.compiles,
                    "evicted": rec.evicted,
                    "signatures": sigs,
                }
            )
    return sorted(out, key=lambda r: r["fn"])


def debug_state(tail: int = 32) -> dict:
    """The /debug/device payload: totals, the per-function registry,
    and the recent compile events carrying trace_id exemplars."""
    return {
        "enabled": enabled(),
        "totals": totals(),
        "tile_budget_mb": tile_budget_mb(),
        "compiles_by_label": {
            f"{fn}|{cause}": n for (fn, cause), n in sorted(compile_totals_by_label().items())
        },
        "registry": registry_state(),
        "recent_compiles": recent_compiles(tail),
    }


# ---------------------------------------------------------------------------
# warmstore inventory plane (jitsig): the signature population persists
# so ROADMAP item 2's warmup_compile_only prewarmer can replay the exact
# shapes a restored process will be asked to solve


def export_signatures() -> List[tuple]:
    """Serializable (fn, static_names, [(arr_part, static_part), ...])
    rows — keys only, counts stay process-local."""
    out: List[tuple] = []
    with _MU:
        for rec in _REGISTRY.values():
            out.append((rec.name, rec.static_names, list(rec.signatures.keys())))
    return out


def import_signatures(rows: List[tuple]) -> Tuple[int, int]:
    """Re-anchor a snapshot's signature inventory into the live
    registry → (restored, dropped). The witness is the live seam: a row
    restores only onto a function this process actually registered
    through ``wrap()`` with the same static-argname contract — anything
    else (renamed fn, changed static set, malformed row) is dropped,
    never trusted. Restored signatures are inventory, not history:
    count 0, flagged ``restored``, and their first live call does not
    raise a recompile event (it is the predicted replay)."""
    restored = dropped = 0
    for row in rows:
        try:
            name, static_names, keys = row
            static_names = tuple(static_names)
        except (TypeError, ValueError):
            dropped += 1
            continue
        with _MU:
            rec = _REGISTRY.get(name)
            if rec is None or rec.static_names != static_names:
                dropped += len(keys) if isinstance(keys, list) else 1
                continue
            for key in keys:
                try:
                    arr, static = key
                    k = (tuple(tuple(x) if isinstance(x, list) else x for x in arr),
                         tuple(tuple(x) if isinstance(x, list) else x for x in static))
                except (TypeError, ValueError):
                    dropped += 1
                    continue
                if k not in rec.signatures:
                    rec.signatures[k] = {"count": 0, "first_ms": None, "restored": True}
                restored += 1
    return restored, dropped


def replay_targets(restored_only: bool = True) -> List[dict]:
    """The prewarmer's shopping list: per registered function, the
    signature keys still flagged ``restored`` (inventory rows imported
    from a snapshot that no live call has replayed yet) plus the live
    observing wrapper to replay them through. ``restored_only=False``
    widens to every known signature (profile tooling)."""
    out: List[dict] = []
    with _MU:
        for rec in _REGISTRY.values():
            if rec.wrapper is None:
                continue
            keys = [
                k
                for k, meta in rec.signatures.items()
                if meta.get("restored") or not restored_only
            ]
            if keys:
                out.append(
                    {
                        "fn": rec.name,
                        "static_names": rec.static_names,
                        "keys": keys,
                        "wrapper": rec.wrapper,
                    }
                )
    return sorted(out, key=lambda r: r["fn"])


def reset() -> None:
    """Drop every registration's signature population and the event
    ring (tests, simulate_process_death). Function records survive —
    they are module-import facts, not runtime state."""
    with _MU:
        for rec in _REGISTRY.values():
            rec.signatures.clear()
            rec.calls = 0
            rec.compiles = 0
            rec.evicted = 0
        _EVENTS.clear()
        _TOTALS["compiles"] = 0
        _TOTALS["calls"] = 0
        _TOTALS["prewarm_compiles"] = 0
        _TRANSFERS.clear()
