"""Slow-solve capture: persist any finished trace whose wall time
exceeds a configurable threshold, so a host-time regression observed
once in production leaves a loadable artifact behind.

Env knobs (read per capture so tests and live operators can flip them
without restarting):

  KARPENTER_TPU_TRACE_SLOW_MS   wall-time threshold in ms; unset/empty
                                disables capture; "0" captures every
                                buffered trace (debug mode)
  KARPENTER_TPU_TRACE_DIR       output directory (created on demand);
                                default /tmp/karpenter-tpu-traces
  KARPENTER_TPU_TRACE_KEEP      max files retained (oldest pruned);
                                default 100

Failures are swallowed after a debug log: the capture path must never
take a solve down.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from .tracer import Trace

DEFAULT_DIR = "/tmp/karpenter-tpu-traces"
DEFAULT_KEEP = 100


def _threshold_ms() -> Optional[float]:
    raw = os.environ.get("KARPENTER_TPU_TRACE_SLOW_MS", "")
    if raw == "":
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def maybe_capture(trace: Trace) -> Optional[str]:
    """Write ``trace`` as Chrome trace-event JSON if it crossed the
    slow-solve threshold. Returns the file path, or None."""
    threshold = _threshold_ms()
    if threshold is None or trace.total_ms < threshold:
        return None
    out_dir = os.environ.get("KARPENTER_TPU_TRACE_DIR", DEFAULT_DIR)
    try:
        from .export import to_chrome_json

        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir,
            f"solve-{trace.wall_start:.3f}-{trace.trace_id}.trace.json",
        )
        with open(path, "w") as f:
            f.write(to_chrome_json([trace]))
        _prune(out_dir)
        return path
    except OSError:
        logging.getLogger("karpenter").debug(
            "slow-solve trace capture failed", exc_info=True
        )
        return None


def _prune(out_dir: str) -> None:
    """Keep the newest KARPENTER_TPU_TRACE_KEEP capture files."""
    try:
        keep = int(os.environ.get("KARPENTER_TPU_TRACE_KEEP", str(DEFAULT_KEEP)))
    except ValueError:
        keep = DEFAULT_KEEP
    try:
        files = sorted(
            f for f in os.listdir(out_dir) if f.endswith(".trace.json")
        )
        for name in files[: max(0, len(files) - keep)]:
            os.unlink(os.path.join(out_dir, name))
    except OSError:
        pass
