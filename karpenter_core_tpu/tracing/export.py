"""Chrome trace-event JSON export (the catapult TraceEvent format that
Perfetto and ``chrome://tracing`` load directly).

Every span becomes one complete event (``ph: "X"``) with microsecond
``ts``/``dur``; metadata events (``ph: "M"``) name the process and the
per-thread tracks. All spans share one monotonic clock
(perf_counter_ns), so events from several traces in one export sequence
correctly on the shared timeline.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List

from .tracer import SYNTHETIC_TID, Trace

# stable small track number for the synthetic device lane; real thread
# idents are remapped to small ints per export for readable track names
_DEVICE_TRACK = 0


def to_chrome_events(trace: Trace) -> List[dict]:
    """One trace → a list of TraceEvent dicts."""
    events: List[dict] = []
    tid_map: Dict[int, int] = {}

    def track(tid: int) -> int:
        if tid == SYNTHETIC_TID:
            return _DEVICE_TRACK
        if tid not in tid_map:
            tid_map[tid] = len(tid_map) + 1
        return tid_map[tid]

    for s in trace.spans:
        ev = {
            "name": s.name,
            "ph": "X",
            "ts": s.ts_ns / 1e3,  # microseconds (may be fractional)
            "dur": s.dur_ns / 1e3,
            "pid": trace.pid,
            "tid": track(s.tid),
            "cat": "solve" if s.tid != SYNTHETIC_TID else "device",
        }
        args = dict(s.args) if s.args else {}
        if s.parent is None:
            args.setdefault("trace_id", trace.trace_id)
        if args:
            ev["args"] = args
        events.append(ev)

    # metadata: name the process once and each thread track
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": trace.pid,
            "tid": 0,
            "args": {"name": f"karpenter-tpu solve {trace.trace_id}"},
        },
        {
            "name": "thread_name",
            "ph": "M",
            "pid": trace.pid,
            "tid": _DEVICE_TRACK,
            "args": {"name": "device (attributed)"},
        },
    ]
    for ident, num in tid_map.items():
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": trace.pid,
                "tid": num,
                "args": {"name": f"host thread {ident}"},
            }
        )
    return meta + events


def to_chrome_json(traces: Iterable[Trace]) -> str:
    """One or more traces → a Chrome trace-event JSON document
    (object form, so top-level metadata is representable)."""
    traces = list(traces)
    events: List[dict] = []
    for t in traces:
        events.extend(to_chrome_events(t))
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "traces": [
                {
                    "trace_id": t.trace_id,
                    "name": t.name,
                    "wall_start": t.wall_start,
                    "total_ms": round(t.total_ms, 3),
                    **({"args": t.args} if t.args else {}),
                }
                for t in traces
            ]
        },
    }
    return json.dumps(doc)
