"""Per-decision flight recorder (ISSUE 10 tentpole): one bounded ring
of decision records, each assembled at plan-emit time from the spans the
cross-thread TraceContext propagation collected under the decision's
root.

A record answers "where did this pod's 90 ms go?" without loading a
Chrome trace: the pod-pending → plan-emitted latency of every pod the
decision settled, queue-wait vs compute split, per-stage self times on
the authoritative lane (they sum to the decision's wall clock — the
root-lane partition invariant the tracer maintains), concurrent-lane
time (prewarm / adopted work overlapping the decision), the
consolidated per-solve stats (cache-hit digest, merge/pack engine and
backend choices, cost/bound/gap when the LP backend priced the plan),
and the trace links (e.g. the N tenant solves coalesced into one
fleet mega-dispatch).

Operational surface:

- ``/debug/decisions[/last]`` (operator/server.py) serves the ring;
- SLO burn-rate gauges: the fraction of decisions over
  ``KARPENTER_TPU_SLO_TARGET_MS`` (default 500 — the paper's headline
  budget) in the trailing 1 m / 10 m windows, pushed to the metrics
  gauge the pipeline attaches;
- breach dumps: when a decision exceeds
  ``KARPENTER_TPU_SLO_BREACH_DUMP_MS``, the record (with its full
  Chrome trace) is persisted under ``KARPENTER_TPU_TRACE_DIR`` exactly
  like the slow-solve capture, newest ``KARPENTER_TPU_TRACE_KEEP``
  kept.

The ring is process-global (``RECORDER``) like the trace ring; tests
construct private instances.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .tracer import Trace

log = logging.getLogger("karpenter.flightrec")

DEFAULT_KEEP = 256
DEFAULT_TARGET_MS = 500.0
# the burn windows (seconds → gauge label); trailing-window fractions of
# decisions over target, the SRE-shaped "are we eating the error budget"
# signal ROADMAP item 3 names for the decision-latency SLO
BURN_WINDOWS = ((60.0, "1m"), (600.0, "10m"))
# a decision's timeline counts as fully reconstructed when the root
# lane's per-stage self times sum to its wall clock within this fraction
RECONSTRUCT_TOL = 0.01


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def slo_target_ms() -> float:
    return _env_float("KARPENTER_TPU_SLO_TARGET_MS", DEFAULT_TARGET_MS)


def _breach_threshold_ms() -> Optional[float]:
    raw = os.environ.get("KARPENTER_TPU_SLO_BREACH_DUMP_MS", "")
    if raw == "":
        return None
    try:
        return float(raw)
    except ValueError:
        return None


# -- chaos fault-window annotation (ISSUE 15) -------------------------------
# The chaos harness registers the active fault window here so a breach
# dump (or a /debug/decisions record) produced while a fault is injected
# is distinguishable from an organic regression. Process-global like
# RECORDER; the window rides every record assembled while it is set.

_FAULT_WINDOW_MU = threading.Lock()
_FAULT_WINDOW: Optional[dict] = None


def set_fault_window(scenario: str, fault: str, phase: str = "active") -> None:
    """Mark records assembled from now on as taken under injected
    chaos: ``scenario`` (e.g. chaos_relist_storm), ``fault`` (the
    kube/faults.py kind), ``phase`` (inject | active | recovery)."""
    global _FAULT_WINDOW
    with _FAULT_WINDOW_MU:
        _FAULT_WINDOW = {"scenario": scenario, "fault": fault, "phase": phase}


def clear_fault_window() -> None:
    global _FAULT_WINDOW
    with _FAULT_WINDOW_MU:
        _FAULT_WINDOW = None


def active_fault_window() -> Optional[dict]:
    with _FAULT_WINDOW_MU:
        return dict(_FAULT_WINDOW) if _FAULT_WINDOW is not None else None


class DecisionRecord(dict):
    """One decision's flight record. A plain dict (JSON-ready for the
    debug routes and breach dumps) with typed access helpers."""

    @property
    def decision_id(self) -> str:
        return self.get("decision_id", "")

    @property
    def reconstructed(self) -> bool:
        return bool(self.get("timeline", {}).get("reconstructed"))


class FlightRecorder:
    """Bounded newest-wins ring of DecisionRecords + SLO burn windows."""

    def __init__(self, capacity: Optional[int] = None, clock=time.monotonic):
        if capacity is None:
            try:
                capacity = int(os.environ.get("KARPENTER_TPU_FLIGHTREC_KEEP", DEFAULT_KEEP))
            except ValueError:
                capacity = DEFAULT_KEEP
        self._mu = threading.Lock()
        self._records: deque = deque(maxlen=max(1, capacity))
        # (monotonic ts, over-target) per decision, pruned past the
        # largest burn window
        self._burn: deque = deque()
        self._seq = 0
        self.clock = clock
        # optional metrics Gauge with a `window` label (the registry's
        # karpenter_tpu_decision_slo_burn_rate); attached by the serving
        # pipeline / fleet scheduler so the recorder stays import-light
        self._burn_gauge = None

    def attach_burn_gauge(self, gauge) -> None:
        with self._mu:
            self._burn_gauge = gauge

    # -- recording -----------------------------------------------------------

    def record(
        self,
        kind: str,
        tick: int,
        trace: Optional[Trace] = None,
        solve: Optional[dict] = None,
        queue_wait_ms: Optional[float] = None,
        latency_ms: Optional[List[float]] = None,
        pods_decided: int = 0,
        errors: int = 0,
        **extra,
    ) -> DecisionRecord:
        """Assemble and retain one decision's record at plan-emit time.

        ``trace`` is the decision's finished root trace (None when
        recording was disabled — the record still lands, flagged
        unreconstructed); ``solve`` is the consolidated
        ``solver.stats.solve_stats`` dict; ``latency_ms`` the
        pod-pending → plan-emitted latencies of the pods this decision
        settled."""
        with self._mu:
            self._seq += 1
            seq = self._seq
        lat = sorted(latency_ms) if latency_ms else []
        rec = DecisionRecord(
            seq=seq,
            kind=kind,
            tick=tick,
            wall_clock=time.time(),
            decision_id=trace.trace_id if trace is not None else f"untraced-{seq}",
            pods_decided=int(pods_decided),
            errors=int(errors),
            latency_ms={
                "max": round(lat[-1], 3) if lat else None,
                "mean": round(sum(lat) / len(lat), 3) if lat else None,
                "count": len(lat),
            },
            timeline=self._timeline(trace, queue_wait_ms),
            solve=solve or {},
            links=list(trace.links) if trace is not None else [],
        )
        if extra:
            rec.update(extra)
        window = active_fault_window()
        if window is not None:
            rec["fault_window"] = window
        # the SLO clock is decision latency when pods were settled,
        # the step's own wall otherwise (an empty tick still burns time)
        slo_ms = rec["latency_ms"]["max"]
        if slo_ms is None:
            slo_ms = rec["timeline"]["wall_ms"]
        rec["slo_ms"] = round(slo_ms, 3) if slo_ms is not None else None
        target = slo_target_ms()
        rec["slo_over"] = bool(slo_ms is not None and slo_ms > target)
        now = self.clock()
        with self._mu:
            self._records.append(rec)
            self._burn.append((now, rec["slo_over"]))
            horizon = now - max(w for w, _ in BURN_WINDOWS)
            while self._burn and self._burn[0][0] < horizon:
                self._burn.popleft()
            gauge = self._burn_gauge
            burn = self._burn_rates_locked(now)
        if gauge is not None:
            for _, label in BURN_WINDOWS:
                gauge.set(burn[label], window=label)
        self._maybe_dump(rec, trace)
        return rec

    @staticmethod
    def _timeline(trace: Optional[Trace], queue_wait_ms: Optional[float]) -> dict:
        if trace is None:
            return {
                "wall_ms": None,
                "queue_wait_ms": queue_wait_ms,
                "stages_ms": {},
                "stages_sum_ms": None,
                "concurrent_ms": {},
                "lanes": 0,
                "reconstructed": False,
            }
        wall = trace.total_ms
        stages = {k: round(v, 3) for k, v in sorted(trace.phase_breakdown_ms().items())}
        stages_sum = sum(stages.values())
        lanes = trace.lane_breakdown_ms()
        concurrent: Dict[str, float] = {}
        for tid, lane in lanes.items():
            if trace.root_tid is not None and tid == trace.root_tid:
                continue
            for name, ms in lane.items():
                concurrent[name] = round(concurrent.get(name, 0.0) + ms, 3)
        return {
            "wall_ms": round(wall, 3),
            "queue_wait_ms": queue_wait_ms,
            "stages_ms": stages,
            "stages_sum_ms": round(stages_sum, 3),
            "concurrent_ms": concurrent,
            "lanes": len(lanes),
            # the acceptance invariant: root-lane self times partition
            # the decision's wall clock (within tolerance + a scheduling
            # jitter floor for sub-ms decisions)
            "reconstructed": bool(
                trace.spans
                and abs(stages_sum - wall) <= max(RECONSTRUCT_TOL * wall, 0.05)
            ),
        }

    def _maybe_dump(self, rec: DecisionRecord, trace: Optional[Trace]) -> None:
        threshold = _breach_threshold_ms()
        if threshold is None or rec["slo_ms"] is None or rec["slo_ms"] <= threshold:
            return
        out_dir = os.environ.get("KARPENTER_TPU_TRACE_DIR", None)
        if out_dir is None:
            from .capture import DEFAULT_DIR

            out_dir = DEFAULT_DIR
        try:
            payload = {"record": rec}
            if trace is not None:
                from .export import to_chrome_events

                payload["trace_events"] = to_chrome_events(trace)
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"decision-{rec['wall_clock']:.3f}-{rec.decision_id}.breach.json"
            )
            with open(path, "w") as f:
                json.dump(payload, f, default=str)
            from .capture import _prune

            _prune(out_dir)
        except (OSError, TypeError, ValueError):
            log.debug("SLO breach dump failed", exc_info=True)

    # -- burn accounting -----------------------------------------------------

    def _burn_rates_locked(self, now: float) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for window, label in BURN_WINDOWS:
            total = over = 0
            for ts, was_over in self._burn:
                if ts >= now - window:
                    total += 1
                    over += was_over
            out[label] = round(over / total, 4) if total else 0.0
        return out

    def burn_rates(self) -> Dict[str, float]:
        with self._mu:
            return self._burn_rates_locked(self.clock())

    # -- consumers -----------------------------------------------------------

    def last(self) -> Optional[DecisionRecord]:
        with self._mu:
            return self._records[-1] if self._records else None

    def all(self) -> List[DecisionRecord]:
        with self._mu:
            return list(self._records)

    def coverage(self, kind: Optional[str] = None) -> Optional[float]:
        """Fraction of retained decisions with a fully reconstructed
        timeline (the ≥99% acceptance metric). None when empty."""
        with self._mu:
            recs = [r for r in self._records if kind is None or r["kind"] == kind]
        if not recs:
            return None
        return sum(1 for r in recs if r.reconstructed) / len(recs)

    def clear(self) -> None:
        with self._mu:
            self._records.clear()
            self._burn.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._records)

    def debug_state(self, tail: int = 32) -> dict:
        """The /debug/decisions payload."""
        with self._mu:
            records = list(self._records)
            capacity = self._records.maxlen
            burn = self._burn_rates_locked(self.clock())
        coverage = (
            round(sum(1 for r in records if r.reconstructed) / len(records), 4)
            if records
            else None
        )
        return {
            "retained": len(records),
            "capacity": capacity,
            "slo_target_ms": slo_target_ms(),
            "burn_rate": burn,
            "coverage": coverage,
            "fault_window": active_fault_window(),
            "decisions": records[-max(1, tail):],
        }


RECORDER = FlightRecorder()
