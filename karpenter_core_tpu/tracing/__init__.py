"""Solve-trace subsystem (SURVEY §5 tracing; the reference's
``--enable-profiling`` pprof surface, operator.go:144-160, taken one
step further): structured span traces of every solve, exportable as
Chrome trace-event JSON (Perfetto / ``chrome://tracing``), with a
metrics bridge into ``solver_phase_duration`` and slow-solve capture
to disk.

Layers:
  tracer.py    — thread-local span stack, monotonic clocks, ring buffer,
                 cross-thread TraceContext capture/adopt + orphan counter
  flightrec.py — per-decision flight recorder: bounded ring of decision
                 records, SLO burn-rate windows, breach dumps
  export.py    — Chrome trace-event JSON (catapult TraceEvent format)
  capture.py   — slow-solve persistence behind env knobs
"""

from .tracer import (  # noqa: F401
    RING,
    Span,
    Trace,
    TraceContext,
    TraceRing,
    adopt,
    capture,
    current_trace,
    current_trace_id,
    enabled,
    orphan_recent,
    orphan_spans,
    reset_orphans,
    span,
    trace_root,
)
from .export import to_chrome_events, to_chrome_json  # noqa: F401
from .capture import maybe_capture  # noqa: F401
from .flightrec import RECORDER, DecisionRecord, FlightRecorder  # noqa: F401
