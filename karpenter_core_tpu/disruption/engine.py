"""Batched disruption engine (ISSUE 7 tentpole): the multi/single-node
consolidation decision as a device-scale subset search.

The reference binary-searches ONE sort order (disruption cost) with a
full scheduling simulation per probe, capped at 100 candidates
(multinodeconsolidation.go:58-137). This engine scores candidate node
**subsets** — every prefix of several sort orders (cost, price saved,
pod count, emptiness), per-pool and per-zone prefixes, plus the
cross-pool merge of per-pool winners — in a single vmapped device
dispatch (``tpu_repack.subset_screen_kernel``, the arbitrary-subset
generalization of the prefix screen), brackets the canonical order with
the true batched repack lower bound (``repack_feasible``), and verifies
only frontier subsets with oracle simulations that run warm through the
PR-4 incremental memos (route / compat rows / job / merge / seed —
``helpers.simulate_scheduling`` reuses a long-lived simulation
scheduler and passes the drained provider-id tuple as
``TPUScheduler.solve(sim_drained=...)``, the seed-key delta).

**Decision contract (the plan-identity gate).** The engine's *chosen
command* follows the sequential oracle's contract exactly: the
canonical (disruption-cost) order's screen/repack bounds produce the
same bounded verification sequence as
``MultiNodeConsolidation.first_n_consolidation``, verification runs the
same ``method._attempt`` / ``method.compute_consolidation`` code, and
the binary-search fallback is literally the sequential method's.
Batched-engine commands are therefore plan-identical to the sequential
path by construction (``KARPENTER_TPU_DISRUPT_ENGINE=sequential``
retains it as the oracle; tests/test_disrupt_engine.py holds the gate
across seeded clusters). The wider subset family contributes pruning
(screen feasibility is downward-closed: an infeasible subset proves
every superset infeasible) and observability (``last_engine_stats``
reports when the family contains a larger feasible subset than the
canonical prefix — the cross-pool winner the sequential order cannot
see), never a divergent command.

**Delta-keyed simulation memos.** Two cross-tick caches (LRU-capped,
env-tunable via ``KARPENTER_TPU_DISRUPT_{BOUNDS,VERIFY}_CACHE_MAX``)
make the steady state cheap, under the PR-4 invariant (reuse is
memoization, never approximation):

- **bounds** — the family's screen/repack bounds, keyed by the ordered
  candidate provider-id tuple + ``Cluster.generation()`` (witnesses
  every informer-fed input: candidate pods, node availability, the
  surviving fleet) + the pool/catalog world key. Any cluster or catalog
  event invalidates.
- **verdicts** — *negative only*: a subset whose drain simulation
  failed (or single-candidate consolidation no-op'd), keyed by the
  **drained-node subset** (sorted provider ids) + generation + world
  key. Successful commands are never cached — they execute and change
  the world. The drained-subset component is what keeps a drained-node
  probe from ever aliasing the undrained solve; the cachesound mutation
  harness (tests/test_cachesound.py) kills key-component drops here.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..tracing import tracer
from ..solver import incremental
from ..utils import pod as podutils
from .types import ACTION_NOOP, Candidate, Command

ENGINE_ENV = "KARPENTER_TPU_DISRUPT_ENGINE"
# subset-family size bound: beyond it, prefix sizes are subsampled per
# order (geometrically toward the full prefix) — never silently: the
# dropped count rides in last_engine_stats["family_capped"]
FAMILY_MAX_ENV = "KARPENTER_TPU_DISRUPT_FAMILY_MAX"
_FAMILY_MAX_DEFAULT = 8192
# alternate-order batched repacks per decision (each is one pack
# dispatch; the canonical order's repack always runs — it is the
# decision bound). Extra orders refine the family's lower bounds only.
ALT_REPACKS_ENV = "KARPENTER_TPU_DISRUPT_ALT_REPACKS"
_ALT_REPACKS_DEFAULT = 2


def engine_mode() -> str:
    """batched (default) | sequential — the PR-2 engine-switch pattern;
    the sequential path is the retained plan-identity oracle."""
    v = os.environ.get(ENGINE_ENV, "batched").strip().lower()
    return v if v in ("batched", "sequential") else "batched"


def _family_max() -> int:
    try:
        return max(16, int(os.environ.get(FAMILY_MAX_ENV, _FAMILY_MAX_DEFAULT)))
    except ValueError:
        return _FAMILY_MAX_DEFAULT


def _alt_repacks() -> int:
    try:
        return max(0, int(os.environ.get(ALT_REPACKS_ENV, _ALT_REPACKS_DEFAULT)))
    except ValueError:
        return _ALT_REPACKS_DEFAULT


@dataclass
class FamilyBounds:
    """One decision's batched bounds: the canonical-order sandwich that
    drives the command, plus the whole family's screen verdicts."""

    k_hi: int  # canonical screen upper bound (screen_prefixes)
    k_lo: int  # canonical repack lower bound (repack_prefixes)
    # per order label: {"screen_k": largest screen-feasible prefix,
    # "repack_k": largest repack-feasible prefix (alt orders only when
    # budgeted), "size": candidates in the order}
    orders: Dict[str, dict] = field(default_factory=dict)
    subsets_screened: int = 0
    screen_feasible: int = 0
    family_capped: int = 0  # subsets dropped by the family-size bound
    # the family's best screen-feasible subset when it beats the
    # canonical prefix (observability only — the decision contract pins
    # the command to the oracle order)
    best_family: Optional[dict] = None


class BatchedDisruptionEngine:
    """Shared by the multi- and single-node consolidation methods; one
    instance per DisruptionController (wired through ctx.engine)."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.bounds = incremental.LRU("disruptbounds")
        self.verdicts = incremental.LRU("disruptverify")
        self.cstats = incremental.CacheStats()
        self.last_engine_stats: Optional[dict] = None

    # -- invalidation witnesses -----------------------------------------

    def _generation(self) -> Optional[int]:
        gen = getattr(self.ctx.cluster, "generation", None)
        return gen() if callable(gen) else None

    def _world_key(self) -> Optional[tuple]:
        """Pool + catalog content witness for the memo keys: every
        nodepool's replay fingerprint and catalog generation/fingerprint
        (solver/incremental.py). None (→ no memoization) when any pool
        or catalog cannot be fingerprinted."""
        try:
            pools = [
                np_
                for np_ in self.ctx.kube_client.list("NodePool")
                if np_.metadata.deletion_timestamp is None
            ]
        except Exception:  # noqa: BLE001 — unprobeable world: skip memoization
            return None
        keys = []
        for np_ in sorted(pools, key=lambda p: p.name):
            try:
                its = self.ctx.cloud_provider.get_instance_types(np_) or []
            except Exception:  # noqa: BLE001 — unprobeable catalog: skip memoization
                return None
            keys.append(
                (
                    incremental.pool_replay_fingerprint(np_),
                    incremental.catalog_key(self.ctx.cloud_provider, np_, its),
                )
            )
        return tuple(keys)

    # -- subset family ---------------------------------------------------

    def _orders(self, candidates: List[Candidate]) -> List[Tuple[str, tuple]]:
        """The structured sort-order family over the (already
        cost-sorted) candidate list: index tuples whose prefixes are the
        engine's subsets. Deduplicated — a single-pool cluster's pool
        order IS the cost order."""
        n = len(candidates)
        idx = list(range(n))

        def resched(c: Candidate) -> int:
            return sum(1 for p in (c.pods or []) if podutils.is_reschedulable(p))

        def used_fraction(c: Candidate) -> float:
            try:
                alloc = c.instance_type.allocatable()
                avail = c.state_node.available()
            except Exception:  # noqa: BLE001 — unpriceable node sorts last
                return 1.0
            fracs = []
            for k, cap in alloc.items():
                cap_f = float(cap)
                if cap_f > 0:
                    fracs.append(1.0 - float(avail.get(k, 0)) / cap_f)
            return max(fracs) if fracs else 1.0

        ordered: List[Tuple[str, tuple]] = [("cost", tuple(idx))]
        ordered.append(
            (
                "price",
                tuple(sorted(idx, key=lambda i: (-(candidates[i].price() or 0.0), i))),
            )
        )
        ordered.append(
            ("pods", tuple(sorted(idx, key=lambda i: (resched(candidates[i]), i))))
        )
        ordered.append(
            (
                "emptiness",
                tuple(sorted(idx, key=lambda i: (used_fraction(candidates[i]), i))),
            )
        )
        pools = sorted({c.nodepool.name for c in candidates})
        if len(pools) > 1:
            for pool in pools:
                sub = tuple(i for i in idx if candidates[i].nodepool.name == pool)
                if len(sub) >= 2:
                    ordered.append((f"pool:{pool}", sub))
        zones = sorted({c.zone for c in candidates})
        if len(zones) > 1:
            for zone in zones:
                sub = tuple(i for i in idx if candidates[i].zone == zone)
                if len(sub) >= 2:
                    ordered.append((f"zone:{zone}", sub))
        out: List[Tuple[str, tuple]] = []
        seen: Dict[tuple, str] = {}
        for label, order in ordered:
            if order in seen:
                continue
            seen[order] = label
            out.append((label, order))
        return out

    @staticmethod
    def _prefix_sizes(order_len: int, budget: int) -> List[int]:
        """Prefix sizes (≥2) to screen for one order under a per-order
        subset budget: all of them when they fit, else a geometric
        subsample that always keeps 2 and the full prefix."""
        full = list(range(2, order_len + 1))
        if len(full) <= budget:
            return full
        picks = np.unique(
            np.geomspace(2, order_len, num=max(2, budget)).round().astype(int)
        )
        return [int(k) for k in picks if 2 <= k <= order_len]

    def _family_masks(
        self, n: int, orders: List[Tuple[str, tuple]]
    ) -> Tuple[np.ndarray, List[Tuple[str, int]], int]:
        """(S, N) membership masks for every family subset plus a
        (order label, prefix size) descriptor per row; the third return
        is the number of subsets dropped by the family-size cap."""
        cap = _family_max()
        total = sum(max(0, len(o) - 1) for _, o in orders)
        per_order = max(4, cap // max(1, len(orders))) if total > cap else n
        rows: List[np.ndarray] = []
        descr: List[Tuple[str, int]] = []
        dropped = 0
        for label, order in orders:
            sizes = self._prefix_sizes(len(order), per_order)
            dropped += max(0, len(order) - 1 - len(sizes))
            mask = np.zeros(n, dtype=bool)
            prev = 0
            for k in sizes:
                mask[list(order[prev:k])] = True
                prev = k
                rows.append(mask.copy())
                descr.append((label, k))
        if not rows:
            return np.zeros((0, n), dtype=bool), [], dropped
        return np.stack(rows), descr, dropped

    # -- batched bounds (cross-tick memoized) ----------------------------

    def _bounds(self, cands: List[Candidate]) -> FamilyBounds:
        gen = self._generation()
        world = self._world_key()
        key = None
        if gen is not None and world is not None:
            # the ordered provider-id tuple is the candidate-set delta;
            # generation witnesses every informer-fed input the bounds
            # read (candidate pods, node availability, fleet free
            # space), the world key witnesses pools + catalogs
            key = (gen, world, tuple(c.provider_id() for c in cands))
            hit = self.bounds.get(key, self.cstats)
            if hit is not None:
                return hit
        fb = self._compute_bounds(cands)
        if key is not None:
            self.bounds.put(key, fb, self.cstats)  # analysis: allow-cache-key(self.ctx)
            # — self.ctx reads are witnessed by (generation, world key):
            # every cluster mutation bumps generation (state/cluster.py),
            # every pool/catalog mutation moves the world key
        return fb

    def _compute_bounds(self, cands: List[Candidate]) -> FamilyBounds:
        from . import tpu_repack

        n = len(cands)
        with tracer.span("disrupt.screen", candidates=n):
            k_hi = tpu_repack.screen_prefixes(self.ctx, cands)
            orders = self._orders(cands)
            masks, descr, dropped = self._family_masks(n, orders)
            feas = (
                tpu_repack.screen_subsets(self.ctx, cands, masks)
                if len(masks)
                else np.zeros(0, dtype=bool)
            )
        fb = FamilyBounds(k_hi=k_hi, k_lo=0)
        fb.subsets_screened = int(len(masks))
        fb.screen_feasible = int(np.count_nonzero(feas))
        fb.family_capped = dropped
        order_screen: Dict[str, int] = {}
        for (label, k), ok in zip(descr, feas):
            if ok:
                order_screen[label] = max(order_screen.get(label, 0), k)
        for label, order in orders:
            fb.orders[label] = {
                "size": len(order),
                "screen_k": order_screen.get(label, 0),
            }
        with tracer.span("disrupt.repack", candidates=n):
            k_lo_vec = tpu_repack.repack_feasible(self.ctx, cands)
            fb.k_lo = (
                int(np.max(np.flatnonzero(k_lo_vec))) + 1 if k_lo_vec.any() else 0
            )
            fb.orders["cost"]["repack_k"] = fb.k_lo
            # refine the most promising non-canonical orders with the
            # true batched repack (bounded: each is one pack dispatch)
            alts = [
                (label, order)
                for label, order in orders
                if label != "cost" and order_screen.get(label, 0) > fb.k_hi
            ]
            alts.sort(key=lambda lo: -order_screen.get(lo[0], 0))
            for label, order in alts[: _alt_repacks()]:
                vec = tpu_repack.repack_feasible(
                    self.ctx, [cands[i] for i in order]
                )
                fb.orders[label]["repack_k"] = (
                    int(np.max(np.flatnonzero(vec))) + 1 if vec.any() else 0
                )
        # cross-pool merge of per-pool winners: union of each pool's
        # largest screen-feasible prefix, screened as one extra subset
        pool_orders = {
            label: order for label, order in orders if label.startswith("pool:")
        }
        if len(pool_orders) > 1:
            union: List[int] = []
            for label, order in pool_orders.items():
                k = order_screen.get(label, 0)
                union.extend(order[:k])
            if len(union) >= 2:
                mask = np.zeros((1, n), dtype=bool)
                mask[0, sorted(set(union))] = True
                with tracer.span("disrupt.screen", candidates=n, crosspool=True):
                    ok = tpu_repack.screen_subsets(self.ctx, cands, mask)
                fb.subsets_screened += 1
                if len(ok) and ok[0]:
                    fb.screen_feasible += 1
                    fb.orders["crosspool"] = {
                        "size": int(mask.sum()),
                        "screen_k": int(mask.sum()),
                    }
        # the family's best feasible subset, for the observability story
        # ("the engine saw a bigger consolidation than the oracle order
        # permits") — never the command source
        best_label, best_k = None, 0
        for label, o in fb.orders.items():
            if o.get("screen_k", 0) > best_k:
                best_label, best_k = label, o["screen_k"]
        if best_label is not None and best_k > fb.k_hi:
            fb.best_family = {"order": best_label, "size": best_k}
        return fb

    # -- delta-keyed verification (negative-verdict memo) ----------------

    def _attempt_multi(
        self, method, cands: List[Candidate], k: int
    ) -> Optional[Command]:
        """One prefix verification through the drained-subset verdict
        memo: a generation-guarded negative verdict skips the
        simulation; anything else runs the sequential method's own
        ``_attempt`` (same spot/price/type guards — identity by shared
        code). Only failures are memoized: successful commands execute
        and change the world."""
        subset = cands[:k]
        gen = self._generation()
        world = self._world_key()
        vkey = None
        if gen is not None and world is not None:
            vkey = (
                "multi",
                gen,
                world,
                tuple(sorted(c.provider_id() for c in subset)),
            )
            known = self.verdicts.get(vkey, self.cstats)
            if known is not None:
                return None  # memoized: this drain set cannot consolidate
        with tracer.span("disrupt.verify", subset=k):
            cmd = method._attempt(subset)
        failed = cmd is None
        if failed and vkey is not None:
            # method carries no decision state beyond ctx (ctx-derived
            # reads are witnessed by generation + world key); k only
            # selects the drained subset, which the sorted provider-id
            # tuple in the key witnesses exactly
            self.verdicts.put(vkey, failed, self.cstats)  # analysis: allow-cache-key(method,k)
        return cmd

    # -- the multi-node decision ----------------------------------------

    def multi_command(self, method, candidates: List[Candidate], max_n: int) -> Command:
        """Batched replacement for ``first_n_consolidation``: same
        decision contract (canonical bounds → descending bounded
        verification → shared binary-search fallback), with the family
        screened in one dispatch and probes running warm."""
        from . import methods as methods_mod

        t0 = time.perf_counter()
        stats: dict = {"engine": "batched", "candidates": len(candidates)}
        self.last_engine_stats = stats
        if len(candidates) < 2:
            return Command()
        max_n = min(max_n, len(candidates))
        cands = candidates[:max_n]
        deadline = self.ctx.clock() + methods_mod.MULTI_NODE_CONSOLIDATION_TIMEOUT
        fb = self._bounds(cands)
        stats.update(
            screen_upper_k=fb.k_hi,
            repack_lower_k=fb.k_lo,
            subsets_screened=fb.subsets_screened,
            screen_feasible_subsets=fb.screen_feasible,
            family_capped=fb.family_capped,
            orders=fb.orders,
        )
        if fb.best_family is not None:
            stats["best_family"] = fb.best_family
        verified = [0]
        try:
            # screen infeasibility at k=2 proves every prefix infeasible
            # (capacity is necessary, infeasibility upward-closed): the
            # no-op is decided with ZERO simulations — the steady-state
            # fast path (first_n_consolidation short-circuits identically)
            if fb.k_hi == 0 and fb.k_lo < 2:
                return Command()
            tries = sorted(
                {k for k in (fb.k_hi, fb.k_hi - 1, fb.k_hi - 2, fb.k_lo) if k >= 2},
                reverse=True,
            )
            if not tries:
                # no usable bounds: the sequential fallback at the
                # reference-sized cap (probes are full simulations,
                # memoized like every other probe)
                return self._binary_search_memo(
                    method, cands, min(max_n, methods_mod.max_parallel()), deadline,
                    verified,
                )
            attempted_min = tries[0]
            for k in tries[:4]:  # bounded verification attempts
                if self.ctx.clock() > deadline:
                    break
                verified[0] += 1
                cmd = self._attempt_multi(method, cands, k)
                if cmd is not None:
                    return cmd
                attempted_min = min(attempted_min, k)
            return self._binary_search_memo(
                method,
                cands,
                min(max_n, attempted_min - 1, methods_mod.max_parallel()),
                deadline,
                verified,
            )
        finally:
            stats["subsets_verified"] = verified[0]
            stats["decision_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
            stats["cache"] = self.cstats.to_dict()

    def _binary_search_memo(
        self, method, candidates: List[Candidate], max_n: int, deadline: float,
        verified: list,
    ) -> Command:
        """``MultiNodeConsolidation._binary_search`` probe-for-probe —
        same ranges, same outcomes — with each probe routed through the
        drained-subset verdict memo (a memoized failure IS the
        simulation's failure at this generation, so skipping the solve
        changes nothing but time)."""
        lo_, hi = 1, max_n - 1
        last = Command()
        while lo_ <= hi:
            if self.ctx.clock() > deadline:
                return last
            mid = (lo_ + hi) // 2
            verified[0] += 1
            cmd = self._attempt_multi(method, candidates, mid + 1)
            if cmd is not None:
                last = cmd
                lo_ = mid + 1
            else:
                hi = mid - 1
        return last

    # -- the condition cohorts (ISSUE 15: expiration / drift) -------------

    def condition_command(self, method, candidates: List[Candidate]) -> Command:
        """Batched dispatch for the condition cohorts: the sequential
        ``ConditionMethod._simulate_in_order`` loop — same order, same
        success criterion, same one-winner contract — with (a) the whole
        cohort screened in one singleton-subset dispatch (in-place
        feasibility is observability: a screen-feasible candidate's
        pods fit surviving capacity, so its drain needs no replacement),
        and (b) known-blocked drains memoized negatively, so a cohort
        that failed to simulate at this generation re-decides without
        re-simulating on the next pass. Blocked candidates re-announce
        via the recorder only on the pass that actually simulates —
        events are telemetry, not plan state, so plan identity to the
        sequential oracle holds probe-for-probe."""
        from . import tpu_repack
        from .helpers import CandidateDeletingError, _blocked, simulate_scheduling

        t0 = time.perf_counter()
        stats: dict = {
            "engine": "batched",
            "cohort": method.type_name,
            "candidates": len(candidates),
        }
        self.last_engine_stats = stats
        screened = inplace = 0
        if len(candidates) > 1:
            with tracer.span(
                "disrupt.screen", candidates=len(candidates), cohort=method.type_name
            ):
                feasible = tpu_repack.screen_singles(self.ctx, candidates)
            screened = len(candidates)
            inplace = int(np.count_nonzero(np.asarray(feasible, dtype=bool)))
        stats["subsets_screened"] = screened
        stats["screen_feasible_subsets"] = inplace
        verified = 0
        gen = self._generation()
        world = self._world_key()
        try:
            for candidate in candidates:
                vkey = None
                if gen is not None and world is not None:
                    # the drain simulation reads only the drained node +
                    # the informer/catalog world — NOT the condition that
                    # nominated it — so a blocked verdict is shared
                    # across the expiration/drift cohorts
                    vkey = ("cond", gen, world, (candidate.provider_id(),))
                    known = self.verdicts.get(vkey, self.cstats)
                    if known is not None:
                        continue  # memoized: this drain cannot schedule its pods
                verified += 1
                with tracer.span("disrupt.verify", subset=1, cohort=method.type_name):
                    try:
                        results = simulate_scheduling(
                            self.ctx.kube_client,
                            self.ctx.cluster,
                            self.ctx.provisioner,
                            [candidate],
                        )
                    except CandidateDeletingError:
                        # transient (mid-deletion) — not memoized: the
                        # sequential loop re-probes it next pass too
                        continue
                if not results.all_non_pending_pods_scheduled():
                    _blocked(
                        self.ctx.recorder,
                        candidate,
                        "Scheduling simulation failed to schedule all pods",
                    )
                    if vkey is not None:
                        # see _attempt_multi: ctx reads are witnessed by
                        # (generation, world key), the drained node by
                        # its provider id
                        self.verdicts.put(vkey, True, self.cstats)  # analysis: allow-cache-key(method)
                    continue
                return Command(
                    candidates=[candidate], replacements=results.new_node_claims
                )
            return Command()
        finally:
            stats["subsets_verified"] = verified
            stats["decision_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
            stats["cache"] = self.cstats.to_dict()

    # -- the single-node decision ----------------------------------------

    def single_command(self, method, candidates: List[Candidate]) -> Command:
        """Batched replacement for the single-node scan: one-dispatch
        feasibility screen (the singleton rows of the subset family),
        then the sequential verify loop with the drained-candidate
        noop memo pruning known-futile simulations."""
        from . import methods as methods_mod
        from . import tpu_repack

        t0 = time.perf_counter()
        stats: dict = {"engine": "batched", "candidates": len(candidates)}
        self.last_engine_stats = stats
        screened = 0
        if len(candidates) > 1:
            with tracer.span("disrupt.screen", candidates=len(candidates)):
                feasible = tpu_repack.screen_singles(self.ctx, candidates)
            screened = len(candidates)
            candidates = [c for c, ok in zip(candidates, feasible) if ok]
        stats["subsets_screened"] = screened
        stats["screen_feasible_subsets"] = len(candidates)
        verified = 0
        deadline = self.ctx.clock() + methods_mod.SINGLE_NODE_CONSOLIDATION_TIMEOUT
        try:
            gen = self._generation()
            world = self._world_key()
            for c in candidates:
                if self.ctx.clock() > deadline:
                    return Command()
                vkey = None
                if gen is not None and world is not None:
                    vkey = ("single", gen, world, (c.provider_id(),))
                    known = self.verdicts.get(vkey, self.cstats)
                    if known is not None:
                        continue  # memoized noop for this drained node
                verified += 1
                with tracer.span("disrupt.verify", subset=1):
                    cmd = method.compute_consolidation([c])
                noop = cmd.action() == ACTION_NOOP
                if noop:
                    if vkey is not None:
                        # see _attempt_multi: ctx reads are witnessed by
                        # (generation, world key), the drained node by
                        # its provider id
                        self.verdicts.put(vkey, noop, self.cstats)  # analysis: allow-cache-key(method)
                    continue
                if not method.validate(cmd):
                    return Command()
                return cmd
            method.mark_consolidated()
            return Command()
        finally:
            stats["subsets_verified"] = verified
            stats["decision_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
            stats["cache"] = self.cstats.to_dict()
