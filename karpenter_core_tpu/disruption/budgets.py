"""Disruption-budget enforcement (the reference's accepted design,
designs/disruption-controls.md — its controller carries the API at
apis/v1beta1/nodepool.go:84-118 plus a TODO at
controllers/disruption/controller.go:121; this build implements it).

Per NodePool and reconcile pass:

    allowed   = most restrictive active budget's nodes value
                (int, or percent of the pool's current nodes, ceil)
    disrupting = pool nodes already being voluntarily disrupted
                 (disruption-tainted, marked for deletion, or queued)
    remaining  = max(0, allowed - disrupting)

Methods consume a snapshot of the map while selecting candidates, so a
command never disrupts more nodes per pool than its remaining budget.
"""

from __future__ import annotations

import math
from typing import Callable, Dict

from ..apis import labels as wk
from ..apis.nodepool import Budget
from ..utils import pod as podutils
from ..utils.cron import budget_is_active

DEFAULT_BUDGET = Budget(nodes="10%")  # nodepool.go:87 kubebuilder default


def resolve_nodes_value(nodes: str, total: int) -> int:
    """A budget's ``nodes``: absolute count or percent of the pool's
    current nodes (ceil, so "10%" of a small pool still allows 1)."""
    value = str(nodes).strip()
    if value.endswith("%"):
        try:
            pct = float(value[:-1])
        except ValueError:
            return total
        return math.ceil(total * pct / 100.0)
    try:
        return max(0, int(value))
    except ValueError:
        return total


def allowed_disruptions(nodepool, total: int, now: float) -> int:
    """Most restrictive active budget; no active budget = no cap."""
    budgets = nodepool.spec.disruption.budgets or [DEFAULT_BUDGET]
    values = [
        resolve_nodes_value(b.nodes, total)
        for b in budgets
        if budget_is_active(b.schedule, b.duration, now)
    ]
    return min(values) if values else total


def _is_disrupting(state_node, queue) -> bool:
    if state_node.marked_for_deletion:
        return True
    # externally-initiated drains (kubectl delete node) consume budget
    # too — filter_candidates already excludes them for the same reason
    if (
        state_node.node is not None
        and state_node.node.metadata.deletion_timestamp is not None
    ):
        return True
    if queue is not None and queue.has_any(state_node.provider_id()):
        return True
    taint = podutils.DISRUPTION_NO_SCHEDULE_TAINT
    return any(taint.match(t) for t in state_node.taints())


def build_disruption_budgets(
    cluster, kube_client, clock: Callable[[], float], queue=None
) -> Dict[str, int]:
    """Remaining voluntary disruptions per NodePool for this pass."""
    now = clock()
    totals: Dict[str, int] = {}
    disrupting: Dict[str, int] = {}

    # read-only scan: budgets only count labels/taints/deletion marks, so
    # iterate the live snapshot (for_each_node) instead of deep-copying
    # every node+pod — the copy was half the steady no-op pass's
    # deep_copy cost at config-9 scale (r06→r07 ledger creep clawback)
    def _count(state_node) -> bool:
        pool = state_node.labels().get(wk.NODEPOOL_LABEL_KEY)
        if not pool:
            return True
        totals[pool] = totals.get(pool, 0) + 1
        if _is_disrupting(state_node, queue):
            disrupting[pool] = disrupting.get(pool, 0) + 1
        return True

    cluster.for_each_node(_count)
    remaining: Dict[str, int] = {}
    for nodepool in kube_client.list("NodePool"):
        total = totals.get(nodepool.name, 0)
        allowed = allowed_disruptions(nodepool, total, now)
        remaining[nodepool.name] = max(0, allowed - disrupting.get(nodepool.name, 0))
    return remaining
