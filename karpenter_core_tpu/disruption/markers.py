"""NodeClaim disruption condition markers: Drifted / Expired / Empty (ref
pkg/controllers/nodeclaim/disruption/{controller,drift,expiration,
emptiness}.go)."""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import (
    COND_DRIFTED,
    COND_EMPTY,
    COND_EXPIRED,
    COND_INITIALIZED,
    NodeClaim,
)
from ..apis.nodepool import CONSOLIDATION_POLICY_WHEN_EMPTY, NodePool
from ..scheduling.requirements import label_requirements, node_selector_requirements
from ..utils import pod as podutils

NODEPOOL_DRIFTED = "NodePoolDrifted"
REQUIREMENTS_DRIFTED = "RequirementsDrifted"


class NodeClaimDisruptionController:
    """disruption/controller.go:72-111: composes the three markers."""

    def __init__(
        self,
        kube_client,
        cloud_provider,
        cluster,
        # analysis: allow-clock(expiry vs creation_timestamp — persisted wall-clock stamps by protocol)
        clock: Callable[[], float] = time.time,
        drift_enabled: bool = True,  # the Drift feature gate (options.go:123)
    ):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.cluster = cluster
        self.clock = clock
        self.drift_enabled = drift_enabled

    def reconcile(self, node_claim: NodeClaim, _index: Optional[dict] = None) -> None:
        if node_claim.metadata.deletion_timestamp is not None:
            return
        nodepool = self.kube_client.get(
            "NodePool", node_claim.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
        )
        if nodepool is None:
            return
        before = self._conditions_snapshot(node_claim)
        self._drift(nodepool, node_claim)
        self._expiration(nodepool, node_claim)
        self._emptiness(nodepool, node_claim, _index)
        # only write back (and fire watch events) on an actual change
        if self._conditions_snapshot(node_claim) != before:
            self.kube_client.apply(node_claim)

    @staticmethod
    def _conditions_snapshot(nc: NodeClaim) -> tuple:
        return tuple(
            sorted((c.type, c.status, c.reason) for c in nc.status.conditions)
        )

    def reconcile_all(self) -> None:
        # one sweep-level index: pods by node name + nodes by provider id,
        # instead of O(claims × cluster) re-listing
        pods_by_node: dict = {}
        for p in self.kube_client.list("Pod"):
            if p.spec.node_name:
                pods_by_node.setdefault(p.spec.node_name, []).append(p)
        nodes_by_pid = {
            n.spec.provider_id: n for n in self.kube_client.list("Node") if n.spec.provider_id
        }
        index = {"pods_by_node": pods_by_node, "nodes_by_pid": nodes_by_pid}
        for nc in self.kube_client.list("NodeClaim"):
            self.reconcile(nc, index)

    # -- drift (drift.go:49-140) -------------------------------------------

    def _drift(self, nodepool: NodePool, nc: NodeClaim) -> None:
        if not self.drift_enabled:
            nc.clear_condition(COND_DRIFTED)
            return
        reason = self._is_drifted(nodepool, nc)
        if reason:
            nc.set_condition(COND_DRIFTED, "True", reason)
        else:
            nc.clear_condition(COND_DRIFTED)

    def _is_drifted(self, nodepool: NodePool, nc: NodeClaim) -> str:
        static = self._static_drift(nodepool, nc)
        if static:
            return static
        req_drift = self._requirements_drift(nodepool, nc)
        if req_drift:
            return req_drift
        try:
            return self.cloud_provider.is_drifted(nc) or ""
        except Exception:  # analysis: allow-broad-except — provider drift probe is
            # advisory; a failing probe must read as not-drifted, never disrupt
            return ""

    @staticmethod
    def _static_drift(nodepool: NodePool, nc: NodeClaim) -> str:
        """drift.go:114 areStaticFieldsDrifted: nodepool-hash annotation
        mismatch."""
        pool_hash = nodepool.metadata.annotations.get(wk.NODEPOOL_HASH_ANNOTATION_KEY)
        claim_hash = nc.metadata.annotations.get(wk.NODEPOOL_HASH_ANNOTATION_KEY)
        if not pool_hash or not claim_hash:
            return ""
        return NODEPOOL_DRIFTED if pool_hash != claim_hash else ""

    @staticmethod
    def _requirements_drift(nodepool: NodePool, nc: NodeClaim) -> str:
        """drift.go:123 areRequirementsDrifted: nodepool requirements no
        longer compatible with the claim's labels."""
        pool_reqs = node_selector_requirements(nodepool.spec.template.requirements)
        claim_labels = label_requirements(nc.metadata.labels)
        if pool_reqs.compatible(claim_labels, frozenset(wk.WELL_KNOWN_LABELS), hint=False) is not None:
            return REQUIREMENTS_DRIFTED
        return ""

    # -- expiration (expiration.go:42-80) ----------------------------------

    def _expiration(self, nodepool: NodePool, nc: NodeClaim) -> None:
        expire_after = nodepool.spec.disruption.expire_after
        if expire_after is None:
            nc.clear_condition(COND_EXPIRED)
            return
        # expire from the node's creation if registered, else the claim's
        node = self._node_for(nc)
        base = node.metadata.creation_timestamp if node is not None else nc.metadata.creation_timestamp
        if self.clock() - base >= expire_after:
            nc.set_condition(COND_EXPIRED, "True", "TTLExpired")
        else:
            nc.clear_condition(COND_EXPIRED)

    # -- emptiness (emptiness.go:46-90) ------------------------------------

    def _emptiness(self, nodepool: NodePool, nc: NodeClaim, index: Optional[dict] = None) -> None:
        d = nodepool.spec.disruption
        if d.consolidation_policy != CONSOLIDATION_POLICY_WHEN_EMPTY or d.consolidate_after is None:
            nc.clear_condition(COND_EMPTY)
            return
        if not nc.status_condition_is_true(COND_INITIALIZED):
            nc.clear_condition(COND_EMPTY)
            return
        node = self._node_for(nc, index)
        if node is None:
            nc.clear_condition(COND_EMPTY)
            return
        if self.cluster is not None and self.cluster.is_node_nominated(node.spec.provider_id):
            nc.clear_condition(COND_EMPTY)
            return
        if index is not None:
            node_pods = index["pods_by_node"].get(node.name, [])
        else:
            node_pods = [p for p in self.kube_client.list("Pod") if p.spec.node_name == node.name]
        pods = [
            p
            for p in node_pods
            if not podutils.is_owned_by_daemonset(p) and not podutils.is_terminal(p)
        ]
        if pods:
            nc.clear_condition(COND_EMPTY)
        else:
            nc.set_condition(COND_EMPTY, "True")

    def _node_for(self, nc: NodeClaim, index: Optional[dict] = None):
        if not nc.status.provider_id:
            return None
        if index is not None:
            return index["nodes_by_pid"].get(nc.status.provider_id)
        for n in self.kube_client.list("Node"):
            if n.spec.provider_id == nc.status.provider_id:
                return n
        return None
