"""Disruption candidates and commands (ref
pkg/controllers/disruption/types.go)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..apis import labels as wk
from ..apis.nodepool import NodePool
from ..cloudprovider.types import InstanceType
from ..kube.objects import Pod
from ..state.statenode import StateNode
from ..utils import pod as podutils

ACTION_NOOP = "no-op"
ACTION_REPLACE = "replace"
ACTION_DELETE = "delete"

POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"


def pod_eviction_cost(pod: Pod) -> float:
    """helpers.go GetPodEvictionCost: base 1.0, scaled by the deletion-cost
    annotation and pod priority. Memoized on the pod object behind its
    resource_version (the podcache ``_karp_memo`` rv-guard pattern):
    candidate collection evaluates this for every bound pod of every
    candidate on every pass — 50k calls per decision at config-9 scale —
    and any annotation/priority edit moves the rv."""
    cached = getattr(pod, "_karp_evict", None)
    rv = pod.metadata.resource_version
    if cached is not None and cached[0] == rv:
        return cached[1]
    cost = 1.0
    deletion_cost = pod.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
    if deletion_cost:
        try:
            # higher deletion cost = more expensive to evict
            cost += float(deletion_cost) / 10.0
        except ValueError:
            pass
    if pod.spec.priority is not None:
        cost += float(pod.spec.priority) / 1e6
    pod._karp_evict = (rv, cost)
    return cost


def disruption_cost(pods: List[Pod]) -> float:
    return sum(pod_eviction_cost(p) for p in pods)


class CandidateError(Exception):
    pass


@dataclass
class Candidate:
    """A node eligible for disruption (types.go:49)."""

    state_node: StateNode
    instance_type: InstanceType
    nodepool: NodePool
    zone: str
    capacity_type: str
    pods: List[Pod]
    disruption_cost: float = 0.0

    def name(self) -> str:
        return self.state_node.name()

    def provider_id(self) -> str:
        return self.state_node.provider_id()

    def annotations(self) -> Dict[str, str]:
        return self.state_node.annotations()

    def price(self) -> Optional[float]:
        offering = self.instance_type.offerings.get(self.capacity_type, self.zone)
        return offering.price if offering else None

    def lifetime_remaining(self, now: float) -> float:
        """Fraction of lifetime left ∈ [0,1] (types.go:139): disruption of
        soon-to-expire nodes is cheap."""
        expire_after = self.nodepool.spec.disruption.expire_after
        if expire_after is None or self.state_node.node is None:
            return 1.0
        age = now - self.state_node.node.metadata.creation_timestamp
        remaining = (expire_after - age) / expire_after
        return min(max(remaining, 0.0), 1.0)


def new_candidate(
    kube_client,
    recorder,
    clock: Callable[[], float],
    node: StateNode,
    nodepool_map: Dict[str, NodePool],
    instance_type_map: Dict[str, Dict[str, InstanceType]],
    queue=None,
    pods_by_node: Optional[Dict[str, List[Pod]]] = None,
    node_owned: bool = False,
) -> Candidate:
    """Build + validate a candidate (types.go:60 NewCandidate); raises
    CandidateError when the node is ineligible.

    ``pods_by_node``: an optional node-name → active-pods index so a
    5k-candidate scan is O(pods) once, not O(candidates × pods).
    ``node_owned``: the caller already owns a fresh copy of ``node``
    (cluster.deep_copy_nodes) — skip the second defensive copy."""
    if node.node is None or node.node_claim is None:
        raise CandidateError("state node doesn't contain both a node and a nodeclaim")
    if node.marked_for_deletion:
        raise CandidateError("state node is marked for deletion")
    if not node.initialized():
        raise CandidateError("state node isn't initialized")
    if queue is not None and queue.has_any(node.provider_id()):
        raise CandidateError("candidate is already being deprovisioned")
    if wk.DO_NOT_DISRUPT_ANNOTATION_KEY in node.annotations():
        raise CandidateError(
            f'disruption is blocked through the "{wk.DO_NOT_DISRUPT_ANNOTATION_KEY}" annotation'
        )
    labels = node.labels()
    for label in (wk.CAPACITY_TYPE_LABEL_KEY, wk.LABEL_TOPOLOGY_ZONE):
        if label not in labels:
            raise CandidateError(f'state node doesn\'t have required label "{label}"')
    nodepool_name = labels.get(wk.NODEPOOL_LABEL_KEY)
    if not nodepool_name:
        raise CandidateError("state node doesn't have the karpenter owner label")
    nodepool = nodepool_map.get(nodepool_name)
    it_map = instance_type_map.get(nodepool_name)
    if nodepool is None or it_map is None:
        raise CandidateError(f'nodepool "{nodepool_name}" can\'t be resolved for state node')
    instance_type = it_map.get(labels.get(wk.LABEL_INSTANCE_TYPE, ""))
    if instance_type is None:
        raise CandidateError(
            f'instance type "{labels.get(wk.LABEL_INSTANCE_TYPE)}" can\'t be resolved'
        )
    if node.nominated(clock()):
        raise CandidateError("state node is nominated for a pending pod")
    if pods_by_node is not None:
        pods = pods_by_node.get(node.node.name, [])
    else:
        pods = [
            p
            for p in kube_client.list("Pod")
            if p.spec.node_name == node.node.name and podutils.is_active(p)
        ]
    candidate = Candidate(
        state_node=node if node_owned else node.deep_copy(),
        instance_type=instance_type,
        nodepool=nodepool,
        capacity_type=labels[wk.CAPACITY_TYPE_LABEL_KEY],
        zone=labels[wk.LABEL_TOPOLOGY_ZONE],
        pods=pods,
    )
    candidate.disruption_cost = disruption_cost(pods) * candidate.lifetime_remaining(clock())
    return candidate


@dataclass
class Command:
    """types.go:147: candidates to remove + replacement claims."""

    candidates: List[Candidate] = field(default_factory=list)
    replacements: List[object] = field(default_factory=list)  # SchedulingNodeClaim

    def action(self) -> str:
        if self.candidates and self.replacements:
            return ACTION_REPLACE
        if self.candidates:
            return ACTION_DELETE
        return ACTION_NOOP

    def __str__(self) -> str:
        names = ", ".join(c.name() for c in self.candidates)
        return f"{self.action()}, terminating {len(self.candidates)} candidates [{names}], replacements {len(self.replacements)}"
