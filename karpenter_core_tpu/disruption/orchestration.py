"""Disruption orchestration queue (ref
pkg/controllers/disruption/orchestration/queue.go): per command, wait
for replacements to come up, then delete the candidates; un-do on
failure."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED

QUEUE_TIMEOUT = 10 * 60.0  # queue.go:51 maxRetryDuration


@dataclass
class QueuedCommand:
    """queue.go:139 NewCommand."""

    candidate_provider_ids: List[str]
    candidate_node_names: List[str]
    replacement_names: List[str]
    method: str
    consolidation_type: str
    created: float
    last_error: Optional[str] = None


class OrchestrationQueue:
    # queue timestamps are in-memory timeout anchors, never persisted —
    # monotonic, immune to skew
    def __init__(self, kube_client, cluster, recorder=None, clock: Callable[[], float] = time.monotonic, metrics=None):
        self.kube_client = kube_client
        self.cluster = cluster
        self.recorder = recorder
        self.clock = clock
        self.metrics = metrics
        self.commands: List[QueuedCommand] = []
        self._by_provider: Dict[str, QueuedCommand] = {}

    def add(self, command, replacement_names: List[str], method: str, consolidation_type: str = "") -> None:
        qc = QueuedCommand(
            candidate_provider_ids=[c.provider_id() for c in command.candidates],
            candidate_node_names=[c.name() for c in command.candidates],
            replacement_names=list(replacement_names),
            method=method,
            consolidation_type=consolidation_type,
            created=self.clock(),
        )
        self.commands.append(qc)
        for pid in qc.candidate_provider_ids:
            self._by_provider[pid] = qc

    def has_any(self, provider_id: str) -> bool:
        """queue.go HasAny: a candidate already being disrupted isn't
        eligible again."""
        return provider_id in self._by_provider

    def reconcile(self) -> None:
        """queue.go:158: drive each command forward; requeue on not-ready,
        unwind on timeout."""
        remaining = []
        for qc in self.commands:
            done = self._reconcile_command(qc)
            if not done:
                remaining.append(qc)
            else:
                for pid in qc.candidate_provider_ids:
                    self._by_provider.pop(pid, None)
        self.commands = remaining

    def _reconcile_command(self, qc: QueuedCommand) -> bool:
        if self.clock() - qc.created > QUEUE_TIMEOUT:
            self._unwind(qc, "timed out waiting for replacements")
            return True
        # all replacements must be Registered + Initialized (queue.go:214)
        for name in qc.replacement_names:
            nc = self.kube_client.get("NodeClaim", name)
            if nc is None:
                self._unwind(qc, f"replacement nodeclaim {name} no longer exists")
                return True
            if not (
                nc.status_condition_is_true(COND_REGISTERED)
                and nc.status_condition_is_true(COND_INITIALIZED)
            ):
                qc.last_error = f"waiting on replacement {name}"
                return False
        # replacements ready: delete candidate claims (termination cascades)
        for pid in qc.candidate_provider_ids:
            for nc in self.kube_client.list("NodeClaim"):
                if nc.status.provider_id == pid:
                    self.kube_client.delete(nc)
        if self.metrics is not None:
            self.metrics.nodeclaims_disrupted.inc(
                method=qc.method, count=len(qc.candidate_provider_ids)
            )
        return True

    def _unwind(self, qc: QueuedCommand, reason: str) -> None:
        """Failure path: un-taint, un-mark, surface the error
        (queue.go:214-277)."""
        qc.last_error = reason
        self.cluster.unmark_for_deletion(*qc.candidate_provider_ids)
        for name in qc.candidate_node_names:
            node = self.kube_client.get("Node", name)
            if node is not None:
                node.spec.taints = [
                    t for t in node.spec.taints if t.key != wk.DISRUPTION_TAINT_KEY
                ]
                self.kube_client.apply(node)
