"""Disruption helpers: candidate filtering, scheduling simulation, price
filtering, PDB limits (ref pkg/controllers/disruption/helpers.go,
pdblimits.go)."""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

from ..apis import labels as wk
from ..cloudprovider.types import InstanceType
from ..kube.objects import Pod
from ..scheduler.builder import NodePoolsNotFoundError, build_scheduler
from ..scheduler.scheduler import Results, SchedulerOptions
from ..utils import pod as podutils
from .types import Candidate, CandidateError, new_candidate


class CandidateDeletingError(Exception):
    pass


class PDBLimits:
    """pdblimits.go:36: can the pods be evicted without violating a PDB?"""

    def __init__(self, kube_client):
        self.kube_client = kube_client
        self.pdbs = kube_client.list("PodDisruptionBudget")
        # a PDBLimits instance is a point-in-time snapshot (one per
        # filter/consistency pass), so each PDB's dynamic budget — a full
        # namespace Pod LIST to compute — is resolved at most once
        self._allowed: dict = {}

    def _disruptions_allowed(self, pdb) -> int:
        from ..lifecycle.node_termination import pdb_disruptions_allowed

        key = (pdb.namespace, pdb.name)
        allowed = self._allowed.get(key)
        if allowed is None:
            allowed = pdb_disruptions_allowed(self.kube_client, pdb)
            self._allowed[key] = allowed
        return allowed

    def can_evict_pods(self, pods: List[Pod]) -> Tuple[str, bool]:
        for pod in pods:
            for pdb in self.pdbs:
                if pdb.namespace == pod.namespace and pdb.selector.matches(pod.metadata.labels):
                    if self._disruptions_allowed(pdb) < 1:
                        return f"{pdb.namespace}/{pdb.name}", False
        return "", True


def has_do_not_disrupt_pod(candidate: Candidate) -> Optional[Pod]:
    for p in candidate.pods:
        # rv-memoized (active ∧ do-not-disrupt) — see disruption_screen_flags
        if podutils.disruption_screen_flags(p)[1]:
            return p
    return None


def filter_candidates(kube_client, recorder, candidates: List[Candidate]) -> List[Candidate]:
    """helpers.go:47 filterCandidates: deleting nodes, PDB-blocked nodes and
    do-not-disrupt pods all block voluntary disruption."""
    pdbs = PDBLimits(kube_client)
    out = []
    for cn in candidates:
        if cn.state_node.node is not None and cn.state_node.node.metadata.deletion_timestamp is not None:
            continue
        pdb_name, ok = pdbs.can_evict_pods(cn.pods)
        if not ok:
            _blocked(recorder, cn, f'PDB "{pdb_name}" prevents pod evictions')
            continue
        blocked_pod = has_do_not_disrupt_pod(cn)
        if blocked_pod is not None:
            _blocked(recorder, cn, f'Pod "{blocked_pod.namespace}/{blocked_pod.name}" has do not evict annotation')
            continue
        out.append(cn)
    return out


def cap_by_budgets(
    candidates: List[Candidate], budgets, recorder=None
) -> List[Candidate]:
    """Enforce per-NodePool disruption budgets on an ordered candidate
    list: keep candidates (highest priority first) while their pool has
    remaining budget. ``budgets`` is the pass's remaining-allowance map
    (budgets.build_disruption_budgets); None disables capping. Dropped
    candidates get a Blocked event naming the budget."""
    if budgets is None:
        return candidates
    remaining = dict(budgets)  # local: only the executed command consumes
    kept: List[Candidate] = []
    for cn in candidates:
        pool = cn.nodepool.name
        left = remaining.get(pool)
        if left is None:  # pool unknown to the pass snapshot: no cap
            kept.append(cn)
            continue
        if left > 0:
            remaining[pool] = left - 1
            kept.append(cn)
        else:
            _blocked(
                recorder, cn, f'Disruption budget for nodepool "{pool}" is exhausted'
            )
    return kept


def _blocked(recorder, candidate: Candidate, message: str) -> None:
    if recorder is not None:
        from ..events import events as ev

        recorder.publish(ev.blocked(candidate.state_node.node, message, message))


def get_candidates(
    cluster,
    kube_client,
    recorder,
    clock: Callable[[], float],
    cloud_provider,
    should_disrupt: Callable[[Candidate], bool],
    queue=None,
) -> List[Candidate]:
    """helpers.go GetCandidates: scan cluster state for disruptable nodes."""
    nodepool_map = {np.name: np for np in kube_client.list("NodePool")}
    instance_type_map: Dict[str, Dict[str, InstanceType]] = {}
    for name, np_ in nodepool_map.items():
        try:
            instance_type_map[name] = {it.name: it for it in cloud_provider.get_instance_types(np_)}
        except Exception as e:  # noqa: BLE001 — one bad pool must not stop disruption
            logging.getLogger("karpenter.disruption").debug(
                "skipping nodepool %s: instance-type fetch failed: %s", name, e
            )
            continue
    pods_by_node: Dict[str, list] = {}
    for p in kube_client.list("Pod"):
        if p.spec.node_name and podutils.disruption_screen_flags(p)[0]:
            pods_by_node.setdefault(p.spec.node_name, []).append(p)
    candidates = []
    for node in cluster.deep_copy_nodes():
        try:
            cn = new_candidate(
                kube_client,
                recorder,
                clock,
                node,
                nodepool_map,
                instance_type_map,
                queue,
                pods_by_node=pods_by_node,
                node_owned=True,  # deep_copy_nodes returned fresh copies
            )
        except CandidateError:
            continue
        if should_disrupt(cn):
            candidates.append(cn)
    return candidates


def simulate_scheduling(
    kube_client, cluster, provisioner, candidates: List[Candidate], trace_ctx=None
) -> Results:
    """helpers.go:73 simulateScheduling: run the scheduler in simulation
    mode over pending + candidate + deleting-node pods minus the candidate
    nodes, rejecting placements on uninitialized nodes.

    When the provisioner runs the TPU backend, the simulation does too:
    the displaced pods pack onto the surviving fleet via the tensor
    existing-capacity path (native/device first-fit) instead of the
    greedy O(P·M) per-pod loop — the same engine the provisioning path
    uses, so decisions agree by construction.

    ``trace_ctx`` (ISSUE 10): the originating decision's TraceContext
    when the probe runs on a thread other than the one that opened the
    disruption pass's root — the probe's spans adopt it so they land
    under the decision instead of orphaning. On the same thread the
    ``trace_root`` below already joins the active trace and ``adopt``
    degrades to a plain span."""
    from ..tracing import tracer

    with tracer.adopt(trace_ctx, "disrupt.simulate.adopt", candidates=len(candidates)):
        with tracer.trace_root(
            "disrupt.simulate", is_solve=True, candidates=len(candidates)
        ):
            return _simulate(kube_client, cluster, provisioner, candidates)


def _simulate(kube_client, cluster, provisioner, candidates: List[Candidate]) -> Results:
    candidate_names = {c.name() for c in candidates}
    nodes = cluster.deep_copy_nodes()
    deleting = [n for n in nodes if n.marked_for_deletion]
    state_nodes = [
        n for n in nodes if not n.marked_for_deletion and n.name() not in candidate_names
    ]
    if any(n.name() in candidate_names for n in deleting):
        raise CandidateDeletingError()

    pods: List[Pod] = provisioner.get_pending_pods()
    for c in candidates:
        pods.extend(p for p in c.pods if podutils.is_reschedulable(p))
    for n in deleting:
        for ns, name in n.pod_requests:
            p = kube_client.get("Pod", name, namespace=ns)
            if p is not None and podutils.is_reschedulable(p):
                pods.append(p)

    nodepools = [
        np_ for np_ in kube_client.list("NodePool") if np_.metadata.deletion_timestamp is None
    ]
    if not nodepools:
        raise NodePoolsNotFoundError("no nodepools found")
    if getattr(provisioner, "use_tpu_solver", False):
        return _simulate_tpu(
            kube_client, cluster, provisioner, pods, state_nodes, nodepools,
            sim_drained=tuple(sorted(c.provider_id() for c in candidates)),
        )
    scheduler = build_scheduler(
        kube_client,
        cluster,
        nodepools,
        provisioner.cloud_provider,
        pods,
        state_nodes=state_nodes,
        daemonset_pods=cluster.get_daemonset_pods(),
        recorder=None,
        opts=SchedulerOptions(simulation_mode=True),
    )
    results = scheduler.solve(pods)
    # placements that depend on uninitialized nodes don't count
    # (helpers.go:108-115)
    for existing in results.existing_nodes:
        if not existing.initialized():
            for p in existing.pods:
                results.pod_errors[p.uid] = (
                    f"would schedule against a non-initialized node {existing.name()}"
                )
                results._pods_by_uid[p.uid] = p
    return results


class PlanReplacementClaim:
    """Adapts a TPU NodePlan to the SchedulingNodeClaim surface the
    disruption decision core and provisioner.create consume: the plan
    pins one instance type (what would actually launch), so price
    filtering and the spot/OD guards operate on that type."""

    def __init__(self, plan, nodepool, pods: List[Pod]):
        from ..scheduler.nodeclaim import NodeClaimTemplate
        from ..scheduling import Requirements

        self.template = NodeClaimTemplate(nodepool)
        self.nodepool_name = plan.nodepool_name
        self.pods = pods
        self.requirements = Requirements(
            *(plan.requirements.values_list() if plan.requirements else ())
        )
        self.instance_type_options = [plan.instance_type]
        self.requests = dict(plan.requests or {})

    def to_node_claim(self, nodepool):
        return self.template.to_node_claim(
            nodepool, self.requirements, self.instance_type_options, self.requests
        )


def _sim_scheduler(kube_client, cluster, provisioner, nodepools):
    """The long-lived simulation TPUScheduler, cached on the provisioner
    while the nodepool set is unchanged (the PR-4 reuse pattern of
    Provisioner._schedule_tpu, on a separate instance so a probe never
    races the live solve's per-solve state). Reuse is what makes probes
    warm: the scheduler's provider-keyed caches (route, compat rows,
    job, merge, seeds) persist across simulations AND are shared with
    the live path — content-addressed, so sharing is free."""
    from ..solver import TPUScheduler

    key = (id(kube_client), id(cluster)) + tuple(
        (id(np_), np_.metadata.resource_version) for np_ in nodepools
    )
    cached = getattr(provisioner, "_sim_tpu_solver", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    solver = TPUScheduler(
        nodepools, provisioner.cloud_provider, kube_client=kube_client, cluster=cluster
    )
    try:
        # the held nodepool list keeps the key's id()s stable
        provisioner._sim_tpu_solver = (key, solver, list(nodepools))
    except Exception:  # noqa: BLE001 — slotted/fake provisioner: fresh per probe
        pass
    return solver


def _simulate_tpu(
    kube_client, cluster, provisioner, pods: List[Pod], state_nodes, nodepools,
    sim_drained: tuple = (),
) -> Results:
    """TPU-backed simulation: one tensor solve over displaced pods +
    surviving fleet; NodePlans adapt to replacement claims.
    ``sim_drained`` (sorted drained provider ids) keys the solve's
    delta-sensitive memos — see TPUScheduler.solve."""
    solver = _sim_scheduler(kube_client, cluster, provisioner, nodepools)
    sr = solver.solve(
        pods,
        state_nodes=state_nodes,
        daemonset_pods=cluster.get_daemonset_pods(),
        sim_drained=sim_drained,
    )
    results = sr.oracle_results or Results()
    results.pod_errors.update(sr.pod_errors)
    results._pods_by_uid.update({p.uid: p for p in pods})
    nodepool_by_name = {np_.name: np_ for np_ in nodepools}
    for plan in sr.node_plans:
        plan_pods = [pods[i] for i in plan.pod_indices]
        results.new_node_claims.append(
            PlanReplacementClaim(plan, nodepool_by_name[plan.nodepool_name], plan_pods)
        )
    # placements that depend on uninitialized nodes don't count
    # (helpers.go:108-115) — tensor placements and oracle ones alike
    for plan in sr.existing_plans:
        if not plan.state_node.initialized():
            for i in plan.pod_indices:
                p = pods[i]
                results.pod_errors[p.uid] = (
                    f"would schedule against a non-initialized node {plan.state_node.name()}"
                )
                results._pods_by_uid[p.uid] = p
    for existing in results.existing_nodes:
        if not existing.initialized():
            for p in existing.pods:
                results.pod_errors[p.uid] = (
                    f"would schedule against a non-initialized node {existing.name()}"
                )
                results._pods_by_uid[p.uid] = p
    return results


def filter_by_price(
    instance_types: List[InstanceType], requirements, max_price: float
) -> List[InstanceType]:
    """Keep instance types with an allowed offering cheaper than max_price
    (consolidation.go filterByPrice)."""
    out = []
    for it in instance_types:
        offerings = it.offerings.available().requirements(requirements)
        cheapest = offerings.cheapest()
        if cheapest is not None and cheapest.price < max_price:
            out.append(it)
    return out


def get_candidate_prices(candidates: List[Candidate]) -> float:
    """Sum of candidate offering prices (consolidation.go
    getCandidatePrices)."""
    total = 0.0
    for c in candidates:
        price = c.price()
        if price is None:
            raise ValueError(
                f"unable to determine offering for {c.instance_type.name}/{c.capacity_type}/{c.zone}"
            )
        total += price
    return total


def instance_types_are_subset(lhs: List[InstanceType], rhs: List[InstanceType]) -> bool:
    rhs_names = {it.name for it in rhs}
    return all(it.name in rhs_names for it in lhs)


def map_candidates(proposed: List[Candidate], current: List[Candidate]) -> List[Candidate]:
    """Intersect proposed command candidates with fresh state (validation.go
    mapCandidates)."""
    current_by_id = {c.provider_id(): c for c in current}
    return [current_by_id[c.provider_id()] for c in proposed if c.provider_id() in current_by_id]
