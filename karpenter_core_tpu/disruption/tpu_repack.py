"""TPU consolidation screen: evaluate every candidate-prefix size in one
batched computation.

The reference binary-searches prefix sizes, paying a full scheduler
simulation per probe (multinodeconsolidation.go:77-137, O(log N) solves,
1-minute budget). The BASELINE north star asks for the prefixes to be
evaluated in parallel instead. This module computes, on device, a
**capacity feasibility screen** for all prefixes at once:

  feasible[k] = the pods of candidates[0..k] fit into
                (free capacity of the surviving fleet) + (one new node)

via a cumulative-sum over candidate pod loads against a psum'd fleet
free-capacity vector — O(N·R) on TPU for all N prefixes, one dispatch.
The screened k is then verified with the oracle simulation (same role as
the reference's Validation re-solve); capacity screening is necessary
but not sufficient (constraints can still reject), so the caller walks
down on verification failure.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..scheduling import resources
from ..solver import devicetime
from ..solver.encode import build_resource_axis, quantize_capacity, quantize_requests
from ..tracing import deviceplane
from .types import Candidate


@deviceplane.observe_jit("disrupt.prefix_screen")
@jax.jit
def prefix_screen_kernel(
    candidate_loads: jnp.ndarray,  # (N, R) int32 — per-candidate pod request sums
    candidate_free: jnp.ndarray,  # (N, R) int32 — per-candidate free capacity
    fleet_free: jnp.ndarray,  # (R,) int32 — free capacity of non-candidate fleet
    new_node_cap: jnp.ndarray,  # (R,) int32 — largest launchable instance
) -> jnp.ndarray:
    """→ (N,) bool: prefix of size k+1 is capacity-feasible.

    Removing candidates[0..k] frees their nodes but orphans their pods;
    the orphans must fit into the remaining fleet's free space — which
    includes the free space of the *not-removed* candidates — plus at
    most one replacement node."""
    # float32 accumulators: int32 would overflow summing up to 100
    # candidates of ~2^30 quantized units; the screen is a heuristic
    # (verified by simulation after) so f32 precision is ample
    loads = candidate_loads.astype(jnp.float32)
    free = candidate_free.astype(jnp.float32)
    cum_load = jnp.cumsum(loads, axis=0)  # (N, R)
    surviving_candidate_free = jnp.sum(free, axis=0)[None, :] - jnp.cumsum(free, axis=0)
    headroom = (
        fleet_free.astype(jnp.float32)[None, :]
        + surviving_candidate_free
        + new_node_cap.astype(jnp.float32)[None, :]
    )
    return jnp.all(cum_load <= headroom, axis=-1)


@deviceplane.observe_jit("disrupt.subset_screen")
@jax.jit
def subset_screen_kernel(
    subset_masks: jnp.ndarray,  # (S, N) bool/float — candidate membership per subset
    candidate_loads: jnp.ndarray,  # (N, R) int32 — per-candidate pod request sums
    candidate_free: jnp.ndarray,  # (N, R) int32 — per-candidate free capacity
    fleet_free: jnp.ndarray,  # (R,) int32 — free capacity of non-candidate fleet
    new_node_cap: jnp.ndarray,  # (R,) int32 — largest launchable instance
) -> jnp.ndarray:
    """→ (S,) bool: removing exactly the masked candidates is
    capacity-feasible — the arbitrary-subset generalization of
    ``prefix_screen_kernel`` (ISSUE 7): the subset's displaced load must
    fit the non-candidate fleet plus the NOT-removed candidates' free
    space plus one replacement node. One dispatch screens the whole
    subset family (every prefix of every sort order, per-pool/per-zone
    prefixes, cross-pool merges) as two (S,N)×(N,R) contractions.

    Feasibility is downward-closed: growing a subset only adds load and
    removes surviving free space, so an infeasible subset proves every
    superset infeasible — which is what lets the engine prune."""
    loads = candidate_loads.astype(jnp.float32)
    free = candidate_free.astype(jnp.float32)
    m = subset_masks.astype(jnp.float32)
    subset_load = m @ loads  # (S, R)
    surviving_candidate_free = (1.0 - m) @ free  # (S, R)
    headroom = (
        fleet_free.astype(jnp.float32)[None, :]
        + surviving_candidate_free
        + new_node_cap.astype(jnp.float32)[None, :]
    )
    return jnp.all(subset_load <= headroom, axis=-1)


@deviceplane.observe_jit("disrupt.single_screen")
@jax.jit
def single_screen_kernel(
    candidate_loads: jnp.ndarray,  # (N, R) int32 — per-candidate pod request sums
    candidate_free: jnp.ndarray,  # (N, R) int32 — per-candidate free capacity
    fleet_free: jnp.ndarray,  # (R,) int32 — free capacity of the rest of the fleet
    new_node_cap: jnp.ndarray,  # (R,) int32 — largest launchable instance
) -> jnp.ndarray:
    """→ (N,) bool: removing candidate i ALONE is capacity-feasible.

    The single-node analogue of ``prefix_screen_kernel``: candidate i's
    orphaned pods must fit the surviving fleet (all other candidates
    stay, so their free capacity counts) plus one replacement node. One
    dispatch screens every candidate — the reference instead pays a full
    scheduling simulation per candidate in its linear scan
    (singlenodeconsolidation.go:42-89, 3 min budget)."""
    loads = candidate_loads.astype(jnp.float32)
    free = candidate_free.astype(jnp.float32)
    others_free = jnp.sum(free, axis=0)[None, :] - free  # all candidates but i
    headroom = (
        fleet_free.astype(jnp.float32)[None, :]
        + others_free
        + new_node_cap.astype(jnp.float32)[None, :]
    )
    return jnp.all(loads <= headroom, axis=-1)


def _encode_candidates(candidates: List[Candidate]):
    """Shared screen encoding: (names, axis, loads, free). Loads count
    ONLY reschedulable pods — daemonset/node-owned pods die with the
    node and the oracle simulation doesn't reschedule them
    (helpers.py simulate_scheduling / utils.pod.is_reschedulable);
    counting them would make the screens falsely reject."""
    from ..utils import pod as podutils

    candidate_names = {c.name() for c in candidates}
    all_requests = [
        resources.requests_for_pods(*(p for p in c.pods if podutils.is_reschedulable(p)))
        if c.pods
        else {}
        for c in candidates
    ]
    instance_types = [c.instance_type for c in candidates]
    axis = build_resource_axis(all_requests, instance_types)
    loads = np.stack([quantize_requests(r, axis) for r in all_requests])
    free = np.stack(
        [quantize_capacity(c.state_node.available(), axis) for c in candidates]
    )
    return candidate_names, axis, loads, free


def _stateful_screen_inputs(ctx, candidates, candidate_names, loads, free):
    """Append the ISSUE-12 stateful axes (host-port feature columns,
    CSI-driver attach columns) to the screen matrices, and return the
    matching fleet/new-node extension builders. Soundness direction is
    preserved (loads under-approximate, capacities over-approximate —
    see solver/constraint_tensors.py), so k_hi == 0 still proves the
    no-op. No-op (zero extra columns) for port/volume-free fleets."""
    from ..solver.constraint_tensors import (
        screen_axes_for_candidates,
        screen_axes_for_fleet,
    )

    feats, drivers, s_loads, s_free, s_new = screen_axes_for_candidates(
        candidates, getattr(ctx, "kube_client", None)
    )
    if s_new.size == 0:
        return loads, free, None, None
    loads = np.hstack([loads, s_loads])
    free = np.hstack([free, s_free])

    def fleet_ext() -> np.ndarray:
        nodes = [
            n
            for n in ctx.cluster.deep_copy_nodes()
            if not n.marked_for_deletion
            and n.name() not in candidate_names
            and n.initialized()
        ]
        return screen_axes_for_fleet(feats, drivers, nodes)

    return loads, free, fleet_ext, s_new


def screen_singles(ctx, candidates: List[Candidate]) -> np.ndarray:
    """(N,) bool feasibility screen for single-candidate consolidation.
    Screen-infeasible candidates cannot consolidate (capacity is a
    necessary condition); feasible ones still go through the oracle
    simulation."""
    if not candidates:
        return np.zeros(0, dtype=bool)
    from ..solver.backend import default_backend

    default_backend()  # pin/probe BEFORE any jnp op: a dead TPU plugin
    # must cost a bounded probe timeout + CPU fallback, not a hung loop
    candidate_names, axis, loads, free = _encode_candidates(candidates)
    fleet_free = _fleet_free(ctx, axis, candidate_names)
    new_node_cap = _largest_launchable(ctx, axis)
    loads, free, fleet_ext, s_new = _stateful_screen_inputs(
        ctx, candidates, candidate_names, loads, free
    )
    if s_new is not None:
        fleet_free = np.concatenate([fleet_free, fleet_ext()])
        new_node_cap = np.concatenate([new_node_cap, s_new])
    with devicetime.track(phase="screen"):
        devicetime.transfer("h2d", loads, free, fleet_free, new_node_cap, phase="screen")
        out = np.asarray(
            single_screen_kernel(
                jnp.asarray(loads),
                jnp.asarray(free),
                jnp.asarray(fleet_free),
                jnp.asarray(new_node_cap),
            )
        )
    devicetime.transfer("d2h", out, phase="screen")
    return out


def screen_subsets(ctx, candidates: List[Candidate], masks: np.ndarray) -> np.ndarray:
    """(S,) bool capacity screen for arbitrary candidate subsets.
    ``masks`` is (S, N) membership over ``candidates``; one device
    dispatch evaluates every subset (see subset_screen_kernel)."""
    masks = np.asarray(masks)
    if not len(candidates) or masks.size == 0:
        return np.zeros(masks.shape[0] if masks.ndim == 2 else 0, dtype=bool)
    from ..solver.backend import default_backend

    default_backend()  # see screen_singles: resolve before any jnp op
    candidate_names, axis, loads, free = _encode_candidates(candidates)
    fleet_free = _fleet_free(ctx, axis, candidate_names)
    new_node_cap = _largest_launchable(ctx, axis)
    loads, free, fleet_ext, s_new = _stateful_screen_inputs(
        ctx, candidates, candidate_names, loads, free
    )
    if s_new is not None:
        fleet_free = np.concatenate([fleet_free, fleet_ext()])
        new_node_cap = np.concatenate([new_node_cap, s_new])
    with devicetime.track(phase="screen"):
        devicetime.transfer(
            "h2d", masks, loads, free, fleet_free, new_node_cap, phase="screen"
        )
        out = np.asarray(
            subset_screen_kernel(
                jnp.asarray(masks.astype(np.float32)),
                jnp.asarray(loads),
                jnp.asarray(free),
                jnp.asarray(fleet_free),
                jnp.asarray(new_node_cap),
            )
        )
    devicetime.transfer("d2h", out, phase="screen")
    return out


def _fleet_free(ctx, axis, candidate_names) -> np.ndarray:
    fleet_free = np.zeros(axis.count, dtype=np.int64)
    for node in ctx.cluster.deep_copy_nodes():
        if node.marked_for_deletion or node.name() in candidate_names:
            continue
        if not node.initialized():
            continue
        fleet_free += quantize_capacity(node.available(), axis)
    return np.minimum(fleet_free, 2**30).astype(np.int32)


def _largest_launchable(ctx, axis) -> np.ndarray:
    new_node_cap = np.zeros(axis.count, dtype=np.int32)
    for np_ in ctx.kube_client.list("NodePool"):
        try:
            for it in ctx.cloud_provider.get_instance_types(np_):
                new_node_cap = np.maximum(new_node_cap, quantize_capacity(it.allocatable(), axis))
        except Exception as e:  # noqa: BLE001 — one bad pool must not stop the repack
            logging.getLogger("karpenter.disruption").debug(
                "skipping nodepool %s: instance-type fetch failed: %s", np_.name, e
            )
            continue
    return new_node_cap


def repack_prefixes(ctx, candidates: List[Candidate]) -> int:
    """Largest prefix size whose displaced pods actually pack (see
    repack_feasible; 0 when none does)."""
    feasible = repack_feasible(ctx, candidates)
    if not feasible.any():
        return 0
    return int(np.max(np.flatnonzero(feasible))) + 1


def repack_feasible(ctx, candidates: List[Candidate]) -> np.ndarray:
    """(N,) bool — per-prefix repack feasibility: entry k-1 is True when
    prefix k's displaced pods actually PACK — a true first-fit against
    per-node free capacity and label/taint admissibility, not a
    capacity sum — onto the non-candidate fleet plus one replacement
    node (SURVEY §7.7's "evaluate candidate prefixes in one batched
    solve"). Called with a reordered candidate list this prices every
    prefix of ANY sort order in one pack — the batched-repack lower
    bound the disruption engine uses per family order.

    One native/device pack prices every prefix at once: pods are packed
    in candidate order, bins only ever fill, so prefix k's pack state is
    a prefix of the single pack sequence. Surviving candidates' free
    space is deliberately excluded (a placement there would be invalid
    for any larger prefix that removes the host), which makes the result
    a LOWER bound on the consolidatable prefix — the optimistic capacity
    screen (screen_prefixes) is the upper bound, and the oracle verifies
    whichever prefix is attempted."""
    from ..solver.encode import extend_axis, group_pods
    from ..solver.pack import run_pack_existing
    from ..solver.solver import existing_node_compat
    from ..utils import pod as podutils

    if len(candidates) < 2:
        return np.zeros(len(candidates), dtype=bool)
    from ..solver.backend import default_backend

    default_backend()  # see screen_singles: resolve before any device op
    candidate_names = {c.name() for c in candidates}
    pods_per_candidate = [
        [p for p in (c.pods or []) if podutils.is_reschedulable(p)] for c in candidates
    ]
    flat_pods = [p for ps in pods_per_candidate for p in ps]
    owner = np.array(
        [ci for ci, ps in enumerate(pods_per_candidate) for _ in ps], dtype=np.int64
    )

    fleet_nodes = [
        n
        for n in ctx.cluster.deep_copy_nodes()
        if not n.marked_for_deletion
        and n.name() not in candidate_names
        and n.initialized()
    ]
    all_requests = [resources.requests_for_pods(p) for p in flat_pods]
    axis = extend_axis(
        build_resource_axis([], [c.instance_type for c in candidates]), all_requests
    )
    new_node_cap = _largest_launchable(ctx, axis)

    N = len(candidates)
    if flat_pods:
        reqs = np.stack([quantize_requests(r, axis) for r in all_requests])
        # candidate-major order (prefix monotonicity), descending within
        # each candidate (queue.go:76 ordering inside the unit)
        order = np.lexsort((-reqs[:, 1], -reqs[:, 0], owner))
        reqs, owner = reqs[order], owner[order]
        flat_sorted = [flat_pods[i] for i in order]

        assign = np.full(len(flat_sorted), -1, dtype=np.int32)
        if fleet_nodes:
            groups = group_pods(flat_sorted)
            sig_of = np.zeros(len(flat_sorted), dtype=np.int32)
            for s, g in enumerate(groups):
                sig_of[np.asarray(g.pod_indices, dtype=np.int64)] = s
            compat = existing_node_compat(groups, fleet_nodes)
            free = np.zeros((len(fleet_nodes), axis.count), dtype=np.int32)
            for m, node in enumerate(fleet_nodes):
                avail = node.available()
                if not any(v < 0 for v in avail.values()):
                    free[m] = quantize_capacity(avail, axis)
            # ISSUE 12: displaced host-port pods ride as feature columns
            # (conflicts with fleet reservations AND between displaced
            # pods are native to the scan); volume-limited nodes mask
            # out per signature. Both only REMOVE placements, so the
            # repack stays a valid lower bound.
            from ..solver.constraint_tensors import (
                PortFeatures,
                node_reserved_ports,
                volume_admit_matrix,
                resolve_group_volumes,
            )

            sig_ports = [g.host_ports() for g in groups]
            if any(sig_ports):
                feats = PortFeatures(sig_ports)
                if feats.count:
                    sig_loads = feats.load_matrix(sig_ports)
                    reqs = np.ascontiguousarray(
                        np.hstack([reqs, sig_loads[sig_of]]), dtype=np.int32
                    )
                    free = np.ascontiguousarray(
                        np.hstack(
                            [
                                free,
                                feats.free_matrix(
                                    [node_reserved_ports(n) for n in fleet_nodes]
                                ),
                            ]
                        ),
                        dtype=np.int32,
                    )
            kc = getattr(ctx, "kube_client", None)
            if kc is not None and any(g.has_volumes for g in groups):
                gvs = [resolve_group_volumes(kc, g) for g in groups]
                compat = compat.astype(bool) & volume_admit_matrix(gvs, fleet_nodes)
            if compat.any():
                assign, _ = run_pack_existing(reqs, sig_of, compat, free)

        # leftovers must fit ONE replacement node: cumulative load per
        # prefix ≤ the largest launchable allocatable, and every leftover
        # pod must individually fit it
        left = assign < 0
        leftover_load = np.zeros((N, axis.count), dtype=np.int64)
        pod_fits_new = np.ones(N, dtype=bool)
        reqs_res = reqs[:, : axis.count]  # resource slice (port columns
        # are per-node state, meaningless on the one-replacement bound)
        for j in np.flatnonzero(left):
            ci = owner[j]
            leftover_load[ci] += reqs_res[j].astype(np.int64)
            if np.any(reqs_res[j] > new_node_cap):
                pod_fits_new[ci] = False
        cum = np.cumsum(leftover_load, axis=0)
        feasible = np.all(cum <= new_node_cap.astype(np.int64)[None, :], axis=1)
        feasible &= np.cumprod(pod_fits_new)[: N].astype(bool)
    else:
        feasible = np.ones(N, dtype=bool)  # nothing displaced: all delete

    return feasible


def screen_prefixes(ctx, candidates: List[Candidate]) -> int:
    """Largest prefix size (≥0) that passes the capacity screen."""
    if len(candidates) < 2:
        return 0
    from ..solver.backend import default_backend

    default_backend()  # see screen_singles: resolve before any jnp op
    candidate_names, axis, loads, free = _encode_candidates(candidates)

    fleet_free = _fleet_free(ctx, axis, candidate_names)
    # the largest instance a replacement could be (upper bound; the oracle
    # verification enforces the real price/compat constraints)
    new_node_cap = _largest_launchable(ctx, axis)
    loads, free, fleet_ext, s_new = _stateful_screen_inputs(
        ctx, candidates, candidate_names, loads, free
    )
    if s_new is not None:
        fleet_free = np.concatenate([fleet_free, fleet_ext()])
        new_node_cap = np.concatenate([new_node_cap, s_new])

    with devicetime.track(phase="screen"):
        devicetime.transfer("h2d", loads, free, fleet_free, new_node_cap, phase="screen")
        feasible = np.asarray(
            prefix_screen_kernel(
                jnp.asarray(loads),
                jnp.asarray(free),
                jnp.asarray(fleet_free),
                jnp.asarray(new_node_cap),
            )
        )
    devicetime.transfer("d2h", feasible, phase="screen")
    if not feasible.any():
        return 0
    # prefix sizes are 1-indexed; find the largest feasible prefix
    return int(np.max(np.flatnonzero(feasible))) + 1
