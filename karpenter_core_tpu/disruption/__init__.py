from .controller import DisruptionController
from .types import Candidate, Command, ACTION_DELETE, ACTION_REPLACE, ACTION_NOOP
from .orchestration import OrchestrationQueue
from .markers import NodeClaimDisruptionController
