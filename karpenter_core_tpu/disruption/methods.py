"""Disruption methods, run in priority order by the controller (ref
pkg/controllers/disruption/{expiration,drift,emptiness,
emptynodeconsolidation,multinodeconsolidation,
singlenodeconsolidation,validation}.go)."""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import COND_DRIFTED, COND_EMPTY, COND_EXPIRED
from ..apis.nodepool import CONSOLIDATION_POLICY_WHEN_UNDERUTILIZED
from ..scheduling import Requirement
from ..kube.objects import OP_IN
from .helpers import (
    CandidateDeletingError,
    _blocked,
    cap_by_budgets,
    filter_by_price,
    filter_candidates,
    get_candidate_prices,
    instance_types_are_subset,
    map_candidates,
    simulate_scheduling,
)
from .types import ACTION_DELETE, ACTION_NOOP, ACTION_REPLACE, Candidate, Command

CONSOLIDATION_TTL = 15.0  # consolidation.go:25
MULTI_NODE_CONSOLIDATION_TIMEOUT = 60.0  # multinodeconsolidation.go:34
SINGLE_NODE_CONSOLIDATION_TIMEOUT = 180.0  # singlenodeconsolidation.go:29
MAX_PARALLEL = 100  # multinodeconsolidation.go:58
# with the TPU prefix screen, the O(log N) per-probe simulations that
# forced the reference's 100-candidate cap become one batched dispatch;
# the cap rises to bound only the post-screen oracle verification
MAX_PARALLEL_TPU_SCREEN = 1000


def max_parallel() -> int:
    """Candidate cap for simulation-per-probe paths (the binary-search
    fallback and the non-screen engine) — env-tunable, defaulting to the
    reference's bound. Every path that pays a full scheduling simulation
    per probe consults THIS cap, including the fallback below a failed
    screen (the screen cap must not leak into probe sizing)."""
    try:
        return max(2, int(os.environ.get("KARPENTER_TPU_DISRUPT_MAX_CANDIDATES", MAX_PARALLEL)))
    except ValueError:
        return MAX_PARALLEL


def max_parallel_tpu_screen() -> int:
    """Candidate cap for the one-dispatch screen paths."""
    try:
        return max(
            2,
            int(
                os.environ.get(
                    "KARPENTER_TPU_DISRUPT_MAX_CANDIDATES_TPU", MAX_PARALLEL_TPU_SCREEN
                )
            ),
        )
    except ValueError:
        return MAX_PARALLEL_TPU_SCREEN


class Method:
    """types.go:38 Method interface."""

    type_name = ""
    consolidation_type = ""

    def should_disrupt(self, candidate: Candidate) -> bool:
        raise NotImplementedError

    def compute_command(self, candidates: List[Candidate]) -> Command:
        raise NotImplementedError

    def _engine(self):
        """The controller-shared batched engine (disruption/engine.py),
        constructed lazily for tests that build methods from a bare
        ctx. Shared by the consolidation family and (ISSUE 15) the
        condition cohorts, so the whole ordered chain rides one memo
        plane."""
        eng = getattr(self.ctx, "engine", None)
        if eng is None:
            from .engine import BatchedDisruptionEngine

            eng = BatchedDisruptionEngine(self.ctx)
            try:
                self.ctx.engine = eng
            except Exception:  # noqa: BLE001 — frozen/legacy ctx: engine stays local
                pass
        return eng


class ConditionMethod(Method):
    """Expiration / Drift / Emptiness: act on status conditions set by the
    marker controller; replacements are counted by simulation. The
    simulate loop dispatches through the batched engine (ISSUE 15) —
    ``engine.condition_command`` is probe-for-probe the sequential loop
    (``_simulate_in_order``, retained as the plan-identity oracle under
    ``KARPENTER_TPU_DISRUPT_ENGINE=sequential``) with the cohort
    screened in one dispatch and known-blocked drains memoized."""

    condition = ""
    needs_replacement = True

    def __init__(self, ctx):
        self.ctx = ctx
        # per-decision observability (mirrors ConsolidationBase): the
        # batched cohort pass's screen/memo stats, read by the
        # controller's _observe_decision and /debug/traces root args
        self.last_decision_stats: Optional[dict] = None

    def should_disrupt(self, candidate: Candidate) -> bool:
        nc = candidate.state_node.node_claim
        return nc is not None and nc.status_condition_is_true(self.condition)

    def _condition_time(self, candidate: Candidate) -> float:
        nc = candidate.state_node.node_claim
        cond = nc.get_condition(self.condition) if nc is not None else None
        # should_disrupt guarantees the condition exists; if filtering ever
        # changes, sort condition-less candidates last, not first
        return cond.last_transition_time if cond is not None else float("inf")

    def compute_command(self, candidates: List[Candidate]) -> Command:
        candidates = filter_candidates(self.ctx.kube_client, self.ctx.recorder, candidates)
        if not candidates:
            return Command()
        # earliest condition transition disrupts first — "most expired" /
        # "earliest drifted" (drift.go:62-71, expiration.go:66-75)
        candidates.sort(key=self._condition_time)
        candidates = cap_by_budgets(candidates, self.ctx.budgets, self.ctx.recorder)
        if not candidates:
            return Command()
        if not self.needs_replacement:
            return Command(candidates=candidates)
        # all EMPTY candidates disrupt in one command — they need no
        # scheduling simulation (drift.go:86-93, expiration.go:90-97;
        # the reference's candidate pods pre-exclude daemonset/node-owned
        # pods, node.go:40-46 — ours hold all active pods, so filter here)
        from ..utils import pod as podutils

        empty = [
            c
            for c in candidates
            if not any(podutils.is_reschedulable(p) for p in c.pods)
        ]
        if empty:
            return Command(candidates=empty)
        from .engine import engine_mode

        if engine_mode() == "batched":
            engine = self._engine()
            cmd = engine.condition_command(self, candidates)
            self.last_decision_stats = engine.last_engine_stats
            return cmd
        return self._simulate_in_order(candidates)

    def _simulate_in_order(self, candidates: List[Candidate]) -> Command:
        # non-empty: one at a time, launching replacement capacity for
        # displaced pods (expiration.go:80-123, drift.go:75-121)
        for candidate in candidates:
            try:
                results = simulate_scheduling(
                    self.ctx.kube_client, self.ctx.cluster, self.ctx.provisioner, [candidate]
                )
            except CandidateDeletingError:
                continue
            if not results.all_non_pending_pods_scheduled():
                _blocked(
                    self.ctx.recorder,
                    candidate,
                    "Scheduling simulation failed to schedule all pods",
                )
                continue
            return Command(candidates=[candidate], replacements=results.new_node_claims)
        return Command()


class Expiration(ConditionMethod):
    condition = COND_EXPIRED
    type_name = "expiration"


class Drift(ConditionMethod):
    condition = COND_DRIFTED
    type_name = "drift"


class Emptiness(ConditionMethod):
    """Fast path: Empty-condition nodes delete without simulation
    (emptiness.go:42-65)."""

    condition = COND_EMPTY
    needs_replacement = False
    type_name = "emptiness"

    def should_disrupt(self, candidate: Candidate) -> bool:
        if not super().should_disrupt(candidate):
            return False
        d = candidate.nodepool.spec.disruption
        if d.consolidate_after is None:
            return False
        nc = candidate.state_node.node_claim
        cond = nc.get_condition(COND_EMPTY)
        return self.ctx.clock() - cond.last_transition_time >= d.consolidate_after


class ConsolidationBase(Method):
    """consolidation.go:27 shared base."""

    type_name = "consolidation"

    def __init__(self, ctx):
        self.ctx = ctx
        self.last_consolidation_state = -1.0
        self._budget_dropped = 0
        # per-decision observability: the screen/repack bounds sandwich
        # (and, on the batched engine, the whole family's stats) — read
        # by the controller, bench config 9, and /debug/traces root args
        self.last_decision_stats: Optional[dict] = None

    def is_consolidated(self) -> bool:
        return self.last_consolidation_state == self.ctx.cluster.consolidation_state()

    def mark_consolidated(self) -> None:
        # budgets are time-varying: candidates dropped by an exhausted
        # budget are pending work the cluster state won't re-signal, so
        # the nothing-to-do dedup must not latch while any were dropped
        if self._budget_dropped:
            return
        self.last_consolidation_state = self.ctx.cluster.consolidation_state()

    def should_disrupt(self, candidate: Candidate) -> bool:
        """consolidation.go:73 ShouldDisrupt."""
        if candidate.annotations().get(wk.DO_NOT_CONSOLIDATE_ANNOTATION_KEY) == "true":
            return False
        d = candidate.nodepool.spec.disruption
        return d.consolidation_policy == CONSOLIDATION_POLICY_WHEN_UNDERUTILIZED

    def sort_and_filter(self, candidates: List[Candidate]) -> List[Candidate]:
        candidates = filter_candidates(self.ctx.kube_client, self.ctx.recorder, candidates)
        candidates = sorted(candidates, key=lambda c: c.disruption_cost)
        # cheapest-to-disrupt keep their place under the per-pool budget cap
        capped = cap_by_budgets(candidates, self.ctx.budgets, self.ctx.recorder)
        self._budget_dropped = len(candidates) - len(capped)
        return capped

    # -- the decision core (consolidation.go:113 computeConsolidation) -----

    def compute_consolidation(self, candidates: List[Candidate]) -> Command:
        try:
            results = simulate_scheduling(
                self.ctx.kube_client, self.ctx.cluster, self.ctx.provisioner, candidates
            )
        except CandidateDeletingError:
            return Command()
        if not results.all_non_pending_pods_scheduled():
            return Command()
        if not results.new_node_claims:
            return Command(candidates=candidates)
        if len(results.new_node_claims) != 1:
            return Command()

        replacement = results.new_node_claims[0]
        candidate_price = get_candidate_prices(candidates)
        replacement.instance_type_options = filter_by_price(
            replacement.instance_type_options, replacement.requirements, candidate_price
        )
        if not replacement.instance_type_options:
            return Command()

        # spot→spot replacement is disallowed; force OD→spot when allowed
        # (consolidation.go:142-169)
        all_spot = all(c.capacity_type == wk.CAPACITY_TYPE_SPOT for c in candidates)
        ct_req = replacement.requirements.get_req(wk.CAPACITY_TYPE_LABEL_KEY)
        if all_spot and ct_req.has(wk.CAPACITY_TYPE_SPOT):
            return Command()
        if ct_req.has(wk.CAPACITY_TYPE_SPOT) and ct_req.has(wk.CAPACITY_TYPE_ON_DEMAND):
            replacement.requirements.add(
                Requirement(wk.CAPACITY_TYPE_LABEL_KEY, OP_IN, [wk.CAPACITY_TYPE_SPOT])
            )
        return Command(candidates=candidates, replacements=[replacement])

    def validate(self, cmd: Command) -> bool:
        v = Validation(self.ctx, self.should_disrupt)
        return v.is_valid(cmd)


class EmptyNodeConsolidation(ConsolidationBase):
    """emptynodeconsolidation.go: delete all empty candidates at once."""

    consolidation_type = "empty"

    def compute_command(self, candidates: List[Candidate]) -> Command:
        if self.is_consolidated():
            return Command()
        candidates = self.sort_and_filter(candidates)
        empty = [c for c in candidates if not c.pods or all(_ignorable(p) for p in c.pods)]
        if not empty:
            self.mark_consolidated()
            return Command()
        # re-check after the TTL that the nodes are still empty
        # (emptynodeconsolidation.go validation loop)
        if self.ctx.validation_sleep is not None:
            self.ctx.validation_sleep(CONSOLIDATION_TTL)
        still_empty = []
        for c in empty:
            pods = [
                p
                for p in self.ctx.kube_client.list("Pod")
                if p.spec.node_name == c.state_node.name() and not _ignorable(p)
            ]
            if not pods and not self.ctx.cluster.is_node_nominated(c.provider_id()):
                still_empty.append(c)
        return Command(candidates=still_empty)


class MultiNodeConsolidation(ConsolidationBase):
    """multinodeconsolidation.go — with the TPU prefix screen replacing the
    log(N)-simulations binary search when available."""

    consolidation_type = "multi"

    def __init__(self, ctx, use_tpu_screen: bool = True):
        super().__init__(ctx)
        self.use_tpu_screen = use_tpu_screen

    def compute_command(self, candidates: List[Candidate]) -> Command:
        from .engine import engine_mode

        if self.is_consolidated():
            return Command()
        candidates = self.sort_and_filter(candidates)
        cap = max_parallel_tpu_screen() if self.use_tpu_screen else max_parallel()
        max_n = min(len(candidates), cap)
        if self.use_tpu_screen and engine_mode() == "batched":
            engine = self._engine()
            cmd = engine.multi_command(self, candidates, max_n)
            self.last_decision_stats = engine.last_engine_stats
        else:
            cmd = self.first_n_consolidation(candidates, max_n)
        if cmd.action() == ACTION_NOOP:
            self.mark_consolidated()
            return cmd
        if not self.validate(cmd):
            return Command()
        return cmd

    def first_n_consolidation(self, candidates: List[Candidate], max_n: int) -> Command:
        """multinodeconsolidation.go:66 firstNConsolidationOption. With the
        TPU screen we jump straight to the largest capacity-feasible prefix
        and walk down on simulation failure; without it, binary search."""
        if len(candidates) < 2:
            return Command()
        max_n = min(max_n, len(candidates))
        deadline = self.ctx.clock() + MULTI_NODE_CONSOLIDATION_TIMEOUT

        order = None
        if self.use_tpu_screen:
            from ..tracing import tracer
            from . import tpu_repack

            # two one-dispatch bounds bracket the answer: the capacity
            # screen is optimistic (upper), the true batched repack is
            # conservative (lower) — together they replace the
            # reference's O(log N) simulation probes with usually ≤3
            # verification solves
            with tracer.span("disrupt.screen", candidates=max_n):
                k_hi = tpu_repack.screen_prefixes(self.ctx, candidates[:max_n])
            with tracer.span("disrupt.repack", candidates=max_n):
                k_lo = tpu_repack.repack_prefixes(self.ctx, candidates[:max_n])
            self.last_decision_stats = {
                "engine": "sequential",
                "candidates": max_n,
                "screen_upper_k": k_hi,
                "repack_lower_k": k_lo,
            }
            # the screen is a sound necessary condition (capacity; same
            # argument the single-node scan uses to prune), and screen
            # infeasibility is upward-closed — a bigger prefix only adds
            # load and removes surviving free space. k_hi == 0 therefore
            # PROVES no multi-node prefix can consolidate: no-op without
            # a single simulation (unless the differently-quantized
            # repack bound disagrees — then its prefix is still tried)
            if k_hi == 0 and k_lo < 2:
                return Command()
            # descending: the two bounds use different capacity sets, so
            # k_lo can exceed the screen's k_hi — unsorted tries would
            # attempt (and return) a smaller prefix before the largest
            # feasible one
            tries = sorted(
                {k for k in (k_hi, k_hi - 1, k_hi - 2, k_lo) if k >= 2}, reverse=True
            )
            if tries:
                order = tries
        if order is None:
            # no usable screen result: the raised TPU cap would make each
            # binary-search probe a near-1000-candidate simulation — fall
            # back to the simulation-sized cap (env-tunable; defaults to
            # the reference's bound, multinodeconsolidation.go:58)
            return self._binary_search(candidates, min(max_n, max_parallel()), deadline)

        attempted_min = order[0]
        for k in order[:4]:  # bounded verification attempts
            if self.ctx.clock() > deadline:
                break
            cmd = self._attempt(candidates[:k])
            if cmd is not None:
                return cmd
            attempted_min = min(attempted_min, k)
        # both bounds over-estimated; binary search the untried sizes
        # below the smallest prefix we actually attempted, capped so each
        # probe's simulation stays reference-sized (env cap: raising
        # KARPENTER_TPU_DISRUPT_MAX_CANDIDATES raises probe sizing too)
        return self._binary_search(
            candidates, min(max_n, attempted_min - 1, max_parallel()), deadline
        )

    def _attempt(self, prefix: List[Candidate]) -> Optional[Command]:
        cmd = self.compute_consolidation(prefix)
        if cmd.action() == ACTION_REPLACE:
            cmd.replacements[0].instance_type_options = filter_out_same_type(
                cmd.replacements[0], prefix
            )
            if not cmd.replacements[0].instance_type_options:
                return None
            return cmd
        if cmd.action() == ACTION_DELETE:
            return cmd
        return None

    def _binary_search(self, candidates: List[Candidate], max_n: int, deadline: float) -> Command:
        lo_, hi = 1, max_n - 1
        last = Command()
        while lo_ <= hi:
            if self.ctx.clock() > deadline:
                return last
            mid = (lo_ + hi) // 2
            cmd = self._attempt(candidates[: mid + 1])
            if cmd is not None:
                last = cmd
                lo_ = mid + 1
            else:
                hi = mid - 1
        return last


class SingleNodeConsolidation(ConsolidationBase):
    """singlenodeconsolidation.go: linear scan, first success wins — with
    a one-dispatch TPU feasibility screen pruning the scan."""

    consolidation_type = "single"

    def __init__(self, ctx, use_tpu_screen: bool = True):
        super().__init__(ctx)
        self.use_tpu_screen = use_tpu_screen

    def compute_command(self, candidates: List[Candidate]) -> Command:
        from .engine import engine_mode

        if self.is_consolidated():
            return Command()
        candidates = self.sort_and_filter(candidates)
        if self.use_tpu_screen and engine_mode() == "batched":
            engine = self._engine()
            cmd = engine.single_command(self, candidates)
            self.last_decision_stats = engine.last_engine_stats
            return cmd
        if self.use_tpu_screen and len(candidates) > 1:
            # capacity screen for ALL candidates in one device dispatch;
            # screen-infeasible ones cannot consolidate, so the linear
            # simulation scan (the 3-minute budget) skips them entirely
            from .tpu_repack import screen_singles

            feasible = screen_singles(self.ctx, candidates)
            candidates = [c for c, ok in zip(candidates, feasible) if ok]
        deadline = self.ctx.clock() + SINGLE_NODE_CONSOLIDATION_TIMEOUT
        for candidate in candidates:
            if self.ctx.clock() > deadline:
                return Command()
            cmd = self.compute_consolidation([candidate])
            if cmd.action() == ACTION_NOOP:
                continue
            if not self.validate(cmd):
                return Command()
            return cmd
        self.mark_consolidated()
        return Command()


class Validation:
    """validation.go: wait out the TTL, rebuild candidates, re-simulate."""

    def __init__(self, ctx, should_disrupt: Callable[[Candidate], bool]):
        self.ctx = ctx
        self.should_disrupt = should_disrupt

    def is_valid(self, cmd: Command) -> bool:
        if self.ctx.validation_sleep is not None:
            self.ctx.validation_sleep(CONSOLIDATION_TTL)
        from .helpers import get_candidates

        fresh = get_candidates(
            self.ctx.cluster,
            self.ctx.kube_client,
            self.ctx.recorder,
            self.ctx.clock,
            self.ctx.cloud_provider,
            self.should_disrupt,
            self.ctx.queue,
        )
        mapped = filter_candidates(
            self.ctx.kube_client, self.ctx.recorder, map_candidates(cmd.candidates, fresh)
        )
        if len(mapped) != len(cmd.candidates):
            return False
        for c in mapped:
            if self.ctx.cluster.is_node_nominated(c.provider_id()):
                return False
        return self._validate_command(cmd, mapped)

    def _validate_command(self, cmd: Command, candidates: List[Candidate]) -> bool:
        """validation.go:110 ValidateCommand."""
        if not candidates:
            return False
        try:
            results = simulate_scheduling(
                self.ctx.kube_client, self.ctx.cluster, self.ctx.provisioner, candidates
            )
        except CandidateDeletingError:
            return False
        if not results.all_non_pending_pods_scheduled():
            return False
        if not results.new_node_claims:
            return not cmd.replacements
        if len(results.new_node_claims) > 1:
            return False
        if not cmd.replacements:
            return False
        # the original replacement's instance types must cover the new
        # simulation's needs (validation.go tail: subset + price re-check)
        return instance_types_are_subset(
            results.new_node_claims[0].instance_type_options,
            cmd.replacements[0].instance_type_options,
        ) or instance_types_are_subset(
            cmd.replacements[0].instance_type_options,
            results.new_node_claims[0].instance_type_options,
        )


def filter_out_same_type(replacement, consolidated: List[Candidate]):
    """multinodeconsolidation.go:142 filterOutSameType: price-sanity — the
    replacement must be cheaper than the cheapest existing instance of any
    type it shares with the candidates."""
    import math

    existing_types = set()
    prices_by_type = {}
    for c in consolidated:
        existing_types.add(c.instance_type.name)
        offering = c.instance_type.offerings.get(c.capacity_type, c.zone)
        if offering is None:
            continue
        prices_by_type[c.instance_type.name] = min(
            prices_by_type.get(c.instance_type.name, math.inf), offering.price
        )
    max_price = math.inf
    for it in replacement.instance_type_options:
        if it.name in existing_types:
            max_price = min(max_price, prices_by_type.get(it.name, math.inf))
    return filter_by_price(replacement.instance_type_options, replacement.requirements, max_price)


def _ignorable(pod) -> bool:
    from ..utils import pod as podutils

    return (
        podutils.is_owned_by_daemonset(pod)
        or podutils.is_terminal(pod)
        or podutils.is_terminating(pod)
    )
