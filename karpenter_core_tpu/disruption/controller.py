"""Disruption controller: the 10 s singleton loop running Methods in
order (ref pkg/controllers/disruption/controller.go)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..apis import labels as wk
from ..kube.objects import EFFECT_NO_SCHEDULE, Taint
from ..provisioning.provisioner import LaunchOptions
from ..tracing import tracer
from ..utils import pod as podutils
from .budgets import build_disruption_budgets
from .engine import BatchedDisruptionEngine
from .helpers import get_candidates
from .methods import (
    Drift,
    Emptiness,
    EmptyNodeConsolidation,
    Expiration,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from .orchestration import OrchestrationQueue
from .types import ACTION_NOOP, Command


@dataclass
class DisruptionContext:
    """Shared dependencies for methods (the `consolidation` struct,
    consolidation.go:28)."""

    kube_client: object
    cluster: object
    provisioner: object
    cloud_provider: object
    recorder: object
    queue: OrchestrationQueue
    # analysis: allow-clock(condition-stamps — compared against persisted last_transition_time wall-clock stamps)
    clock: Callable[[], float] = time.time
    # test hook: replaces the 15 s validation wait (consolidation.go:42);
    # None skips waiting entirely
    validation_sleep: Optional[Callable[[float], None]] = None
    # remaining voluntary disruptions per nodepool, rebuilt each pass
    # (disruption-controls.md); None = budgets not enforced (legacy tests)
    budgets: Optional[dict] = None
    # the controller-shared batched disruption engine (engine.py);
    # methods construct one lazily when absent (bare-ctx tests)
    engine: Optional[object] = None


class DisruptionController:
    """controller.go:72-136."""

    def __init__(
        self,
        kube_client,
        cluster,
        provisioner,
        cloud_provider,
        recorder=None,
        # analysis: allow-clock(condition-stamps — fans to DisruptionContext, compared against persisted wall-clock stamps)
        clock: Callable[[], float] = time.time,
        queue: Optional[OrchestrationQueue] = None,
        validation_sleep: Optional[Callable[[float], None]] = None,
        use_tpu_screen: bool = True,
        metrics=None,
    ):
        self.kube_client = kube_client
        self.cluster = cluster
        self.clock = clock
        self.metrics = metrics
        self.queue = queue or OrchestrationQueue(kube_client, cluster, recorder, clock, metrics)
        self.ctx = DisruptionContext(
            kube_client=kube_client,
            cluster=cluster,
            provisioner=provisioner,
            cloud_provider=cloud_provider,
            recorder=recorder,
            queue=self.queue,
            clock=clock,
            validation_sleep=validation_sleep,
        )
        # the controller-shared batched engine (engine.py): one instance
        # so its delta-keyed bounds/verdict memos persist across passes
        if use_tpu_screen:
            self.ctx.engine = BatchedDisruptionEngine(self.ctx)
        # method order is the disruption priority (controller.go:72-85)
        self.methods = [
            Expiration(self.ctx),
            Drift(self.ctx),
            Emptiness(self.ctx),
            EmptyNodeConsolidation(self.ctx),
            MultiNodeConsolidation(self.ctx, use_tpu_screen=use_tpu_screen),
            SingleNodeConsolidation(self.ctx, use_tpu_screen=use_tpu_screen),
        ]
        # per-decision bounds/engine stats of the last pass that computed
        # any (bench config 9 and /debug/traces read this)
        self.last_decision_stats: Optional[dict] = None
        # the last pass's trace (the serving pipeline's disruption stage
        # flight-records it per pass)
        self.last_trace = None

    def reconcile(self) -> Optional[str]:
        """One pass; returns the executed method name or None. The pass
        is span-traced (disrupt.{collect,screen,repack,verify,execute})
        into the same solver_phase_duration bridge the solve path feeds;
        passes that ran a simulation land in /debug/traces with the
        engine's subset/bounds stats as root args."""
        if not self.cluster.synced():
            return None
        sink = self.metrics.solver_phase_duration if self.metrics is not None else None
        with tracer.trace_root("disrupt", metrics_sink=sink, buffer_if="solve") as tr:
            self.last_trace = tr
            return self._reconcile(tr)

    def _reconcile(self, tr) -> Optional[str]:
        self._cleanup_stale_taints()
        # per-pass remaining disruption allowance per nodepool; methods
        # cap candidate selection against a snapshot of this map
        self.ctx.budgets = build_disruption_budgets(
            self.cluster, self.kube_client, self.clock, self.queue
        )
        for method in self.methods:
            with tracer.span("disrupt.collect", method=method.type_name):
                candidates = get_candidates(
                    self.cluster,
                    self.kube_client,
                    self.ctx.recorder,
                    self.clock,
                    self.ctx.cloud_provider,
                    method.should_disrupt,
                    self.queue,
                )
            if self.metrics is not None:
                self.metrics.eligible_nodes.set(
                    len(candidates), method=method.type_name
                )
            if not candidates:
                continue
            t0 = time.perf_counter()
            method.last_decision_stats = None
            cmd = method.compute_command(candidates)
            self._observe_decision(method, time.perf_counter() - t0, tr)
            if cmd.action() == ACTION_NOOP:
                continue
            if tr is not None:
                tr.contains_solve = True  # executing passes always buffer
            with tracer.span("disrupt.execute", method=method.type_name):
                self._execute(cmd, method)
            return method.type_name
        return None

    def _observe_decision(self, method, elapsed: float, tr) -> None:
        """Surface one decision's screen-bounds sandwich + subset
        counters (metrics, /debug/traces root args, last_decision_stats)."""
        if self.metrics is not None:
            self.metrics.disruption_evaluation_duration.observe(
                elapsed, method=method.type_name
            )
        stats = getattr(method, "last_decision_stats", None)
        if not stats:
            return
        self.last_decision_stats = stats
        if tr is not None:
            # a decision ran (screens dispatched, maybe zero sims): the
            # pass is buffer-worthy even when the screen proved the
            # no-op without a simulation
            tr.contains_solve = True
            tr.args.setdefault("disrupt", {})[
                getattr(method, "consolidation_type", "") or method.type_name
            ] = stats
        if self.metrics is not None:
            screened = stats.get("subsets_screened")
            if screened:
                self.metrics.disruption_subsets.inc(screened, stage="screened")
            verified = stats.get("subsets_verified")
            if verified:
                self.metrics.disruption_subsets.inc(verified, stage="verified")

    # -- execute (controller.go:177-213) -----------------------------------

    def _execute(self, cmd: Command, method) -> None:
        # 1. cordon candidates with the disruption taint
        for c in cmd.candidates:
            node = self.kube_client.get("Node", c.name())
            if node is not None:
                taint = podutils.DISRUPTION_NO_SCHEDULE_TAINT
                if not any(taint.match(t) for t in node.spec.taints):
                    node.spec.taints.append(
                        Taint(key=taint.key, value=taint.value, effect=taint.effect)
                    )
                self.kube_client.apply(node)
        # 2. launch replacements
        replacement_names: List[str] = []
        if cmd.replacements:
            replacement_names, errs = self.ctx.provisioner.create_node_claims(
                cmd.replacements, LaunchOptions(reason=method.type_name)
            )
            if errs:
                # roll back: un-cordon AND delete any partially created
                # replacements so an aborted command leaks no capacity
                # (controller.go:189-199)
                for name in replacement_names:
                    nc = self.kube_client.get("NodeClaim", name)
                    if nc is not None:
                        self.kube_client.delete(nc)
                for c in cmd.candidates:
                    node = self.kube_client.get("Node", c.name())
                    if node is not None:
                        node.spec.taints = [
                            t for t in node.spec.taints if t.key != wk.DISRUPTION_TAINT_KEY
                        ]
                        self.kube_client.apply(node)
                return
        # 3. mark for deletion + hand to orchestration
        self.cluster.mark_for_deletion(*[c.provider_id() for c in cmd.candidates])
        self.queue.add(cmd, replacement_names, method.type_name, getattr(method, "consolidation_type", ""))
        if self.ctx.recorder is not None:
            from ..events import events as ev

            for c in cmd.candidates:
                self.ctx.recorder.publish(
                    ev.disrupt_node(c.state_node.node, method.type_name)
                )
        if self.metrics is not None:
            self.metrics.disruption_actions.inc(
                method=method.type_name, action=cmd.action()
            )

    def _cleanup_stale_taints(self) -> None:
        """Remove disruption taints from nodes no orchestration command owns
        — crash-safe restart behavior (controller.go:111-118)."""
        for node in self.kube_client.list("Node"):
            if any(t.key == wk.DISRUPTION_TAINT_KEY for t in node.spec.taints):
                pid = node.spec.provider_id
                if not self.queue.has_any(pid) and not self._marked(pid):
                    node.spec.taints = [
                        t for t in node.spec.taints if t.key != wk.DISRUPTION_TAINT_KEY
                    ]
                    self.kube_client.apply(node)

    def _marked(self, provider_id: str) -> bool:
        for n in self.cluster.deep_copy_nodes():
            if n.provider_id() == provider_id:
                return n.marked_for_deletion
        return False
