"""In-memory Kubernetes API store.

The reference tests against a real apiserver (envtest); our equivalent
is this in-memory store with the semantics controllers rely on:
get/list/create/update/delete, label-selector filtering, finalizer-aware
deletion, and watch callbacks. It is both the test control plane and the
default runtime store for simulation.
"""

from __future__ import annotations

import copy
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, Iterable, List, Optional

from .objects import KubeObject, LabelSelector


class Conflict(Exception):
    pass


class NotFound(Exception):
    pass


# watch event types
ADDED, MODIFIED, DELETED = "ADDED", "MODIFIED", "DELETED"


class KubeClient:
    """Thread-safe in-memory object store keyed by (kind, namespace, name).

    ``clock`` stamps deletion timestamps; inject the same clock the
    controllers use so timestamp comparisons agree under simulated time.
    """

    # analysis: allow-clock(object-stamps — creation/deletionTimestamp are persisted wall clock by k8s protocol)
    def __init__(self, clock: Callable[[], float] = time.time) -> None:
        self._objects: Dict[str, Dict[tuple, KubeObject]] = defaultdict(dict)
        self._watchers: Dict[str, List[Callable]] = defaultdict(list)
        self._lock = threading.RLock()
        self._rv = 0
        self.clock = clock
        # admission chain (defaulting + validating webhooks / CEL equivalent,
        # ref pkg/webhooks/webhooks.go:57-87): callables run on create/update
        # before the object is stored; they may mutate (defaults) or raise.
        self.admission: List[Callable[[KubeObject], None]] = []

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _key(obj: KubeObject) -> tuple:
        return (obj.namespace, obj.name)

    def _notify(self, event: str, obj: KubeObject) -> None:
        # deliberately outside self._lock: watch callbacks reenter the
        # client (informers re-list, controllers read state) and would
        # deadlock or invert lock order if notified under it
        for cb in list(self._watchers.get(obj.kind, ())):  # analysis: allow-lock-discipline
            cb(event, obj)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: KubeObject) -> KubeObject:
        for adm in self.admission:
            adm(obj)
        with self._lock:
            kind = obj.kind
            key = self._key(obj)
            if key in self._objects[kind]:
                raise Conflict(f"{kind} {key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
        self._notify(ADDED, obj)
        return obj

    def get(self, kind: str, name: str, namespace: str = "") -> Optional[KubeObject]:
        with self._lock:
            return self._objects[kind].get((namespace, name))

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[LabelSelector] = None,
        filter_fn: Optional[Callable[[KubeObject], bool]] = None,
    ) -> List[KubeObject]:
        with self._lock:
            objs = list(self._objects[kind].values())
        if namespace is not None:
            objs = [o for o in objs if o.namespace == namespace]
        if label_selector is not None:
            objs = [o for o in objs if label_selector.matches(o.metadata.labels)]
        if filter_fn is not None:
            objs = [o for o in objs if filter_fn(o)]
        return objs

    def update(self, obj: KubeObject) -> KubeObject:
        for adm in self.admission:
            adm(obj)
        with self._lock:
            kind = obj.kind
            key = self._key(obj)
            stored = self._objects[kind].get(key)
            if stored is None:
                raise NotFound(f"{kind} {key} not found")
            # optimistic concurrency, apiserver-style: an update carrying a
            # resourceVersion must match the stored one; an unset (0)
            # resourceVersion is an unconditional update. Same-instance
            # updates (the in-memory sharing model) always match.
            if (
                stored is not obj
                and obj.metadata.resource_version
                and obj.metadata.resource_version != stored.metadata.resource_version
            ):
                raise Conflict(
                    f"{kind} {key}: object has been modified "
                    f"(resourceVersion {obj.metadata.resource_version} != "
                    f"{stored.metadata.resource_version})"
                )
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
        self._notify(MODIFIED, obj)
        return obj

    def apply(self, obj: KubeObject) -> KubeObject:
        """Create-or-update convenience. The existence probe holds
        ``_lock`` but the write itself must not: update/create deliver
        watch callbacks through ``_notify``, and holding the lock across
        them would invert the client/controller lock order (see
        ``_notify``). A racing create or delete between probe and write
        is absorbed by retrying in the other mode."""
        for _ in range(3):
            with self._lock:
                exists = self._key(obj) in self._objects[obj.kind]
            try:
                return self.update(obj) if exists else self.create(obj)
            except NotFound:
                continue  # deleted between probe and update → retry as create
            except Conflict:
                if exists:
                    raise  # genuine resourceVersion conflict
                continue  # created between probe and create → retry as update
        return self.update(obj)

    def retry_on_conflict(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        mutate: Callable[[KubeObject], None] = lambda obj: None,
        attempts: int = 5,
    ) -> KubeObject:
        """controller-runtime ``RetryOnConflict`` equivalent: GET, apply
        ``mutate``, UPDATE; on Conflict re-GET the current version and
        retry. The store's controllers share instances and never conflict;
        adapters over a real apiserver (which hand out copies) do."""
        last: Optional[Conflict] = None
        for _ in range(attempts):
            obj = self.get(kind, name, namespace=namespace)
            if obj is None:
                raise NotFound(f"{kind} ({namespace!r}, {name!r}) not found")
            # mutate a copy so a rejected write (conflict, admission)
            # leaves the stored instance untouched — the copy's matching
            # resourceVersion lets a clean retry land
            obj = copy.deepcopy(obj)
            mutate(obj)
            try:
                return self.update(obj)
            except Conflict as err:
                last = err
        raise last if last is not None else Conflict(f"{kind} {name}: retries exhausted")

    def delete(self, obj_or_kind, name: str = "", namespace: str = "") -> bool:
        """Finalizer-aware delete: sets deletionTimestamp when finalizers
        remain, removes otherwise (apiserver semantics the termination
        controllers depend on)."""
        with self._lock:
            if isinstance(obj_or_kind, KubeObject):
                kind, key = obj_or_kind.kind, self._key(obj_or_kind)
            else:
                kind, key = obj_or_kind, (namespace, name)
            obj = self._objects[kind].get(key)
            if obj is None:
                return False
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = self.clock()
                    self._rv += 1
                    obj.metadata.resource_version = self._rv
                    modified = obj
                else:
                    return True
            else:
                del self._objects[kind][key]
                modified = None
        if modified is not None:
            self._notify(MODIFIED, modified)
        else:
            self._notify(DELETED, obj)
        return True

    def remove_finalizer(self, obj: KubeObject, finalizer: str) -> None:
        """Drop a finalizer; if the object is terminating and none remain,
        actually remove it."""
        with self._lock:
            if finalizer in obj.metadata.finalizers:
                obj.metadata.finalizers.remove(finalizer)
            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                self._objects[obj.kind].pop(self._key(obj), None)
                gone = True
            else:
                self._rv += 1
                obj.metadata.resource_version = self._rv
                gone = False
        self._notify(DELETED if gone else MODIFIED, obj)

    # -- watches -----------------------------------------------------------

    def watch(self, kind: str, callback: Callable[[str, KubeObject], None]) -> Callable[[], None]:
        """Register a watch callback; returns an unsubscribe fn. New watches
        receive synthetic ADDED events for existing objects (informer
        list+watch semantics)."""
        with self._lock:
            existing = list(self._objects[kind].values())
            self._watchers[kind].append(callback)
        for obj in existing:
            callback(ADDED, obj)

        def unsubscribe():
            with self._lock:
                if callback in self._watchers[kind]:
                    self._watchers[kind].remove(callback)

        return unsubscribe
