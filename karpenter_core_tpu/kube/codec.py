"""Kubernetes JSON ↔ dataclass codec for the object kinds the
controllers watch — the wire half of the real-apiserver adapter
(restclient.py). The reference gets this from client-go's generated
deepcopy/scheme machinery (operator.go:105-171); here it is explicit,
stdlib-only translation.

Quantities: the apiserver speaks strings ("100m", "2Gi"); internally
everything is integer nanos (kube.quantity). Timestamps: RFC3339 ↔
epoch floats. Unknown fields are ignored on decode; encode emits only
what the controllers set.
"""

from __future__ import annotations

import calendar
import time
from typing import Dict, Optional

from ..apis.nodeclaim import (
    Condition,
    KubeletConfiguration,
    NodeClaim,
    NodeClaimResources,
    NodeClaimSpec,
    NodeClassReference,
)
from ..apis.nodepool import (
    Budget,
    Disruption,
    NodeClaimTemplateObjectMeta,
    NodeClaimTemplateSpec,
    NodePool,
)
from .objects import (
    Affinity,
    ConfigMap,
    Container,
    ContainerPort,
    CSINode,
    CSINodeDriver,
    DaemonSet,
    KubeObject,
    LabelSelector,
    LabelSelectorRequirement,
    Lease,
    Namespace,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodCondition,
    PodDisruptionBudget,
    PreferredSchedulingTerm,
    ResourceRequirements,
    StorageClass,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)
from .quantity import format_quantity, parse_quantity

# kind → (api path prefix, plural, namespaced)
API_PATHS: Dict[str, tuple] = {
    "Pod": ("/api/v1", "pods", True),
    "Node": ("/api/v1", "nodes", False),
    "Namespace": ("/api/v1", "namespaces", False),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "PersistentVolumeClaim": ("/api/v1", "persistentvolumeclaims", True),
    "PersistentVolume": ("/api/v1", "persistentvolumes", False),
    "DaemonSet": ("/apis/apps/v1", "daemonsets", True),
    "PodDisruptionBudget": ("/apis/policy/v1", "poddisruptionbudgets", True),
    "StorageClass": ("/apis/storage.k8s.io/v1", "storageclasses", False),
    "CSINode": ("/apis/storage.k8s.io/v1", "csinodes", False),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases", True),
    "NodePool": ("/apis/karpenter.sh/v1beta1", "nodepools", False),
    "NodeClaim": ("/apis/karpenter.sh/v1beta1", "nodeclaims", False),
}


# kind → dataclass (ghost objects for relist-diff DELETED events, etc.)
OBJECT_TYPES: Dict[str, type] = {
    "Pod": Pod,
    "Node": Node,
    "Namespace": Namespace,
    "ConfigMap": ConfigMap,
    "PersistentVolumeClaim": PersistentVolumeClaim,
    "PersistentVolume": PersistentVolume,
    "DaemonSet": DaemonSet,
    "PodDisruptionBudget": PodDisruptionBudget,
    "StorageClass": StorageClass,
    "CSINode": CSINode,
    "Lease": Lease,
    "NodePool": NodePool,
    "NodeClaim": NodeClaim,
}


def _ts(value) -> Optional[float]:
    if not value:
        return None
    return float(calendar.timegm(time.strptime(value[:19], "%Y-%m-%dT%H:%M:%S")))


def _rfc3339(ts: Optional[float]) -> Optional[str]:
    if ts is None:
        return None
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def _rfc3339_micro(ts: Optional[float]) -> Optional[str]:
    """metav1.MicroTime: the apiserver REQUIRES a six-digit fraction.
    Rounded in integer microseconds so a fraction near 1.0 carries into
    the seconds instead of emitting an invalid 7-digit fraction."""
    if ts is None:
        return None
    secs, micros = divmod(int(round(ts * 1e6)), 10**6)
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(secs)) + f".{micros:06d}Z"


def _resources(d: Optional[dict]) -> dict:
    return {k: parse_quantity(v) for k, v in (d or {}).items()}


def _resources_out(r: dict) -> dict:
    return {k: format_quantity(v) for k, v in (r or {}).items()}


def _selector(d: Optional[dict]) -> Optional[LabelSelector]:
    if d is None:
        return None
    return LabelSelector(
        match_labels=dict(d.get("matchLabels") or {}),
        match_expressions=[
            LabelSelectorRequirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=list(e.get("values") or []),
            )
            for e in d.get("matchExpressions") or []
        ],
    )


def _selector_out(sel: Optional[LabelSelector]) -> Optional[dict]:
    if sel is None:
        return None
    out: dict = {}
    if sel.match_labels:
        out["matchLabels"] = dict(sel.match_labels)
    if sel.match_expressions:
        out["matchExpressions"] = [
            {"key": e.key, "operator": e.operator, "values": list(e.values)}
            for e in sel.match_expressions
        ]
    return out


def _nsr(e: dict) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(
        key=e.get("key", ""),
        operator=e.get("operator", "In"),
        values=list(e.get("values") or []),
    )


def _nsr_out(r: NodeSelectorRequirement) -> dict:
    out = {"key": r.key, "operator": r.operator}
    if r.values:
        out["values"] = list(r.values)
    return out


def _term(d: dict) -> PodAffinityTerm:
    return PodAffinityTerm(
        topology_key=d.get("topologyKey", ""),
        label_selector=_selector(d.get("labelSelector")),
        namespaces=list(d.get("namespaces") or []),
        namespace_selector=_selector(d.get("namespaceSelector")),
    )


def _affinity(d: Optional[dict]) -> Optional[Affinity]:
    if not d:
        return None
    aff = Affinity()
    na = d.get("nodeAffinity")
    if na:
        req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
        aff.node_affinity = NodeAffinity(
            required=(
                NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(
                            match_expressions=[
                                _nsr(e) for e in t.get("matchExpressions") or []
                            ]
                        )
                        for t in req.get("nodeSelectorTerms") or []
                    ]
                )
                if req
                else None
            ),
            preferred=[
                PreferredSchedulingTerm(
                    weight=p.get("weight", 1),
                    preference=NodeSelectorTerm(
                        match_expressions=[
                            _nsr(e)
                            for e in (p.get("preference") or {}).get("matchExpressions")
                            or []
                        ]
                    ),
                )
                for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
            ],
        )
    for key, cls, attr in (
        ("podAffinity", PodAffinity, "pod_affinity"),
        ("podAntiAffinity", PodAntiAffinity, "pod_anti_affinity"),
    ):
        pa = d.get(key)
        if pa:
            setattr(
                aff,
                attr,
                cls(
                    required=[
                        _term(t)
                        for t in pa.get("requiredDuringSchedulingIgnoredDuringExecution")
                        or []
                    ],
                    preferred=[
                        WeightedPodAffinityTerm(
                            weight=w.get("weight", 1),
                            pod_affinity_term=_term(w.get("podAffinityTerm") or {}),
                        )
                        for w in pa.get(
                            "preferredDuringSchedulingIgnoredDuringExecution"
                        )
                        or []
                    ],
                ),
            )
    if aff.node_affinity is None and aff.pod_affinity is None and aff.pod_anti_affinity is None:
        return None
    return aff


def _taints(items) -> list:
    return [
        Taint(key=t.get("key", ""), value=t.get("value", ""), effect=t.get("effect", ""))
        for t in items or []
    ]


def _taints_out(taints) -> list:
    return [{"key": t.key, "value": t.value, "effect": t.effect} for t in taints or []]


def _meta_in(obj: KubeObject, meta: dict) -> None:
    m = obj.metadata
    m.name = meta.get("name", "")
    m.namespace = meta.get("namespace", obj.metadata.namespace)
    m.uid = meta.get("uid", m.uid)
    m.labels = dict(meta.get("labels") or {})
    m.annotations = dict(meta.get("annotations") or {})
    m.finalizers = list(meta.get("finalizers") or [])
    rv = meta.get("resourceVersion")
    if rv is not None:
        try:
            m.resource_version = int(rv)
        except ValueError:
            m.resource_version = 0
    m.generation = meta.get("generation", 1)
    ct = _ts(meta.get("creationTimestamp"))
    if ct is not None:
        m.creation_timestamp = ct
    m.deletion_timestamp = _ts(meta.get("deletionTimestamp"))
    from .objects import OwnerReference

    m.owner_references = [
        OwnerReference(
            api_version=o.get("apiVersion", ""),
            kind=o.get("kind", ""),
            name=o.get("name", ""),
            uid=o.get("uid", ""),
            controller=o.get("controller", False),
            block_owner_deletion=o.get("blockOwnerDeletion", False),
        )
        for o in meta.get("ownerReferences") or []
    ]


def _meta_out(obj: KubeObject) -> dict:
    m = obj.metadata
    out: dict = {"name": m.name}
    if m.namespace:
        out["namespace"] = m.namespace
    if m.labels:
        out["labels"] = dict(m.labels)
    if m.annotations:
        out["annotations"] = dict(m.annotations)
    # ALWAYS present: merge-patch replaces lists wholesale, so clearing
    # the last finalizer must send [] (omission would leave it in place)
    out["finalizers"] = list(m.finalizers)
    if m.resource_version:
        out["resourceVersion"] = str(m.resource_version)
    if m.owner_references:
        out["ownerReferences"] = [
            {
                "apiVersion": o.api_version,
                "kind": o.kind,
                "name": o.name,
                "uid": o.uid,
                "controller": o.controller,
                "blockOwnerDeletion": o.block_owner_deletion,
            }
            for o in m.owner_references
        ]
    return out


# -- decoders ---------------------------------------------------------------


def _decode_pod(d: dict) -> Pod:
    pod = Pod()
    spec = d.get("spec") or {}
    pod.spec.node_name = spec.get("nodeName", "")
    pod.spec.node_selector = dict(spec.get("nodeSelector") or {})
    pod.spec.affinity = _affinity(spec.get("affinity"))
    pod.spec.tolerations = [
        Toleration(
            key=t.get("key", ""),
            operator=t.get("operator", "Equal"),
            value=t.get("value", ""),
            effect=t.get("effect", ""),
            toleration_seconds=t.get("tolerationSeconds"),
        )
        for t in spec.get("tolerations") or []
    ]
    pod.spec.topology_spread_constraints = [
        TopologySpreadConstraint(
            max_skew=c.get("maxSkew", 1),
            topology_key=c.get("topologyKey", ""),
            when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
            label_selector=_selector(c.get("labelSelector")),
            min_domains=c.get("minDomains"),
        )
        for c in spec.get("topologySpreadConstraints") or []
    ]
    for field_name, attr in (("containers", "containers"), ("initContainers", "init_containers")):
        setattr(
            pod.spec,
            attr,
            [
                Container(
                    name=c.get("name", ""),
                    resources=ResourceRequirements(
                        requests=_resources((c.get("resources") or {}).get("requests")),
                        limits=_resources((c.get("resources") or {}).get("limits")),
                    ),
                    ports=[
                        ContainerPort(
                            host_port=p.get("hostPort", 0),
                            container_port=p.get("containerPort", 0),
                            protocol=p.get("protocol", "TCP"),
                            host_ip=p.get("hostIP", ""),
                        )
                        for p in c.get("ports") or []
                    ],
                )
                for c in spec.get(field_name) or []
            ],
        )
    pod.spec.overhead = _resources(spec.get("overhead"))
    pod.spec.volumes = [
        Volume(
            name=v.get("name", ""),
            persistent_volume_claim=(v.get("persistentVolumeClaim") or {}).get("claimName"),
            ephemeral=bool(v.get("ephemeral")),
        )
        for v in spec.get("volumes") or []
    ]
    pod.spec.priority = spec.get("priority")
    pod.spec.priority_class_name = spec.get("priorityClassName", "")
    pod.spec.scheduler_name = spec.get("schedulerName", "default-scheduler")
    status = d.get("status") or {}
    pod.status.phase = status.get("phase", "Pending")
    pod.status.conditions = [
        PodCondition(
            type=c.get("type", ""),
            status=c.get("status", ""),
            reason=c.get("reason", ""),
        )
        for c in status.get("conditions") or []
    ]
    start = _ts(status.get("startTime"))
    if start is not None:
        pod.status.start_time = start
    return pod


def _decode_node(d: dict) -> Node:
    node = Node()
    spec = d.get("spec") or {}
    node.spec.provider_id = spec.get("providerID", "")
    node.spec.taints = _taints(spec.get("taints"))
    node.spec.unschedulable = bool(spec.get("unschedulable", False))
    status = d.get("status") or {}
    node.status.capacity = _resources(status.get("capacity"))
    node.status.allocatable = _resources(status.get("allocatable"))
    node.status.phase = status.get("phase", "")
    node.status.conditions = [
        Condition(
            type=c.get("type", ""),
            status=c.get("status", ""),
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            last_transition_time=_ts(c.get("lastTransitionTime")) or 0.0,
        )
        for c in status.get("conditions") or []
    ]
    return node


def _zones_from_node_affinity(d: Optional[dict]) -> list:
    """Zone values from a PV's spec.nodeAffinity required terms."""
    zones = []
    req = (d or {}).get("required") or {}
    for term in req.get("nodeSelectorTerms") or []:
        for e in term.get("matchExpressions") or []:
            if e.get("key") in (
                "topology.kubernetes.io/zone",
                "failure-domain.beta.kubernetes.io/zone",
            ):
                zones.extend(e.get("values") or [])
    return zones


def _decode_nodepool(d: dict) -> NodePool:
    np_ = NodePool()
    spec = d.get("spec") or {}
    tmpl = spec.get("template") or {}
    tmeta = tmpl.get("metadata") or {}
    tspec = tmpl.get("spec") or {}
    np_.spec.template = NodeClaimTemplateSpec(
        metadata=NodeClaimTemplateObjectMeta(
            labels=dict(tmeta.get("labels") or {}),
            annotations=dict(tmeta.get("annotations") or {}),
        ),
        taints=_taints(tspec.get("taints")),
        startup_taints=_taints(tspec.get("startupTaints")),
        requirements=[_nsr(e) for e in tspec.get("requirements") or []],
        kubelet=_decode_kubelet(tspec.get("kubelet")),
        node_class_ref=_decode_class_ref(tspec.get("nodeClassRef")),
    )
    dis = spec.get("disruption") or {}
    np_.spec.disruption = Disruption(
        consolidate_after=_duration(dis.get("consolidateAfter")),
        consolidation_policy=dis.get("consolidationPolicy", "WhenUnderutilized"),
        expire_after=_duration(dis.get("expireAfter")),
        budgets=[
            Budget(
                nodes=str(b.get("nodes", b.get("maxUnavailable", "10%"))),
                schedule=b.get("schedule", b.get("crontab")),
                duration=_duration(b.get("duration")),
            )
            for b in dis.get("budgets") or []
        ],
    )
    np_.spec.limits = _resources(spec.get("limits"))
    np_.spec.weight = spec.get("weight")
    np_.status.resources = _resources((d.get("status") or {}).get("resources"))
    return np_


def _decode_kubelet(d: Optional[dict]) -> Optional[KubeletConfiguration]:
    if not d:
        return None
    return KubeletConfiguration(
        max_pods=d.get("maxPods"),
        pods_per_core=d.get("podsPerCore"),
        system_reserved=_resources(d.get("systemReserved")),
        kube_reserved=_resources(d.get("kubeReserved")),
        eviction_hard=dict(d.get("evictionHard") or {}),
        eviction_soft=dict(d.get("evictionSoft") or {}),
    )


def _decode_class_ref(d: Optional[dict]) -> Optional[NodeClassReference]:
    if not d:
        return None
    return NodeClassReference(
        name=d.get("name", ""), kind=d.get("kind", ""), api_version=d.get("apiVersion", "")
    )


def _duration(v) -> Optional[float]:
    """metav1.Duration string / 'Never' → seconds."""
    if v is None or v == "Never":
        return None
    if isinstance(v, (int, float)):
        return float(v)
    total, num = 0.0, ""
    for ch in str(v):
        if ch.isdigit() or ch == ".":
            num += ch
        else:
            mult = {"h": 3600.0, "m": 60.0, "s": 1.0}.get(ch)
            if mult is None or not num:
                return None
            total += float(num) * mult
            num = ""
    return total


def _duration_out(seconds: Optional[float]) -> Optional[str]:
    if seconds is None:
        return "Never"
    out = ""
    rest = int(seconds)
    for unit, mult in (("h", 3600), ("m", 60), ("s", 1)):
        n, rest = divmod(rest, mult)
        if n:
            out += f"{n}{unit}"
    return out or "0s"


def _decode_nodeclaim(d: dict) -> NodeClaim:
    nc = NodeClaim()
    spec = d.get("spec") or {}
    nc.spec = NodeClaimSpec(
        taints=_taints(spec.get("taints")),
        startup_taints=_taints(spec.get("startupTaints")),
        requirements=[_nsr(e) for e in spec.get("requirements") or []],
        resources=NodeClaimResources(
            requests=_resources((spec.get("resources") or {}).get("requests"))
        ),
        kubelet=_decode_kubelet(spec.get("kubelet")),
        node_class_ref=_decode_class_ref(spec.get("nodeClassRef")),
    )
    status = d.get("status") or {}
    nc.status.node_name = status.get("nodeName", "")
    nc.status.provider_id = status.get("providerID", "")
    nc.status.image_id = status.get("imageID", "")
    nc.status.capacity = _resources(status.get("capacity"))
    nc.status.allocatable = _resources(status.get("allocatable"))
    nc.status.conditions = [
        Condition(
            type=c.get("type", ""),
            status=c.get("status", ""),
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            last_transition_time=_ts(c.get("lastTransitionTime")) or 0.0,
        )
        for c in status.get("conditions") or []
    ]
    return nc


def _term_out(t: PodAffinityTerm) -> dict:
    out: dict = {"topologyKey": t.topology_key}
    if t.label_selector is not None:
        out["labelSelector"] = _selector_out(t.label_selector)
    if t.namespaces:
        out["namespaces"] = list(t.namespaces)
    if t.namespace_selector is not None:
        out["namespaceSelector"] = _selector_out(t.namespace_selector)
    return out


def _affinity_out(aff: Optional[Affinity]) -> Optional[dict]:
    if aff is None:
        return None
    out: dict = {}
    na = aff.node_affinity
    if na is not None:
        node: dict = {}
        if na.required is not None:
            node["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [
                    {"matchExpressions": [_nsr_out(e) for e in t.match_expressions]}
                    for t in na.required.node_selector_terms
                ]
            }
        if na.preferred:
            node["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {
                    "weight": p.weight,
                    "preference": {
                        "matchExpressions": [
                            _nsr_out(e) for e in p.preference.match_expressions
                        ]
                    },
                }
                for p in na.preferred
            ]
        out["nodeAffinity"] = node
    for attr, key in (
        ("pod_affinity", "podAffinity"),
        ("pod_anti_affinity", "podAntiAffinity"),
    ):
        pa = getattr(aff, attr)
        if pa is not None:
            out[key] = {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    _term_out(t) for t in pa.required
                ],
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": w.weight, "podAffinityTerm": _term_out(w.pod_affinity_term)}
                    for w in pa.preferred
                ],
            }
    return out or None


def _encode_pod_spec(spec) -> dict:
    out: dict = {
        "containers": [
            {
                "name": c.name,
                "resources": {
                    "requests": _resources_out(c.resources.requests),
                    "limits": _resources_out(c.resources.limits),
                },
                "ports": [
                    {
                        "hostPort": p.host_port,
                        "containerPort": p.container_port,
                        "protocol": p.protocol,
                    }
                    for p in c.ports
                ],
            }
            for c in spec.containers
        ],
    }
    if spec.node_name:
        out["nodeName"] = spec.node_name
    if spec.node_selector:
        out["nodeSelector"] = dict(spec.node_selector)
    aff = _affinity_out(spec.affinity)
    if aff:
        out["affinity"] = aff
    if spec.tolerations:
        out["tolerations"] = [
            {
                "key": t.key,
                "operator": t.operator,
                "value": t.value,
                "effect": t.effect,
            }
            for t in spec.tolerations
        ]
    if spec.topology_spread_constraints:
        out["topologySpreadConstraints"] = [
            {
                "maxSkew": c.max_skew,
                "topologyKey": c.topology_key,
                "whenUnsatisfiable": c.when_unsatisfiable,
                **(
                    {"labelSelector": _selector_out(c.label_selector)}
                    if c.label_selector is not None
                    else {}
                ),
                **({"minDomains": c.min_domains} if c.min_domains is not None else {}),
            }
            for c in spec.topology_spread_constraints
        ]
    if spec.volumes:
        vols = []
        for v in spec.volumes:
            if v.persistent_volume_claim:
                vols.append(
                    {
                        "name": v.name,
                        "persistentVolumeClaim": {"claimName": v.persistent_volume_claim},
                    }
                )
            elif v.ephemeral:
                # minimal generic-ephemeral marker so the flag round-trips
                vols.append(
                    {"name": v.name, "ephemeral": {"volumeClaimTemplate": {"spec": {}}}}
                )
            else:
                # a source-less volume is invalid on the wire
                vols.append({"name": v.name, "emptyDir": {}})
        out["volumes"] = vols
    if spec.overhead:
        out["overhead"] = _resources_out(spec.overhead)
    if spec.priority is not None:
        out["priority"] = spec.priority
    return out


def from_k8s(kind: str, d: dict) -> KubeObject:
    """Decode one apiserver JSON object into the internal dataclass."""
    decoders = {
        "Pod": _decode_pod,
        "Node": _decode_node,
        "NodePool": _decode_nodepool,
        "NodeClaim": _decode_nodeclaim,
    }
    dec = decoders.get(kind)
    if dec is not None:
        obj = dec(d)
    elif kind == "DaemonSet":
        obj = DaemonSet()
        tmpl = ((d.get("spec") or {}).get("template") or {}).get("spec") or {}
        obj.pod_template_spec = _decode_pod({"spec": tmpl}).spec
    elif kind == "PodDisruptionBudget":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        obj = PodDisruptionBudget(
            selector=_selector(spec.get("selector")) or LabelSelector(),
            min_available=_intstr(spec.get("minAvailable")),
            max_unavailable=_intstr(spec.get("maxUnavailable")),
            disruptions_allowed=status.get("disruptionsAllowed", 0),
        )
    elif kind == "PersistentVolumeClaim":
        spec = d.get("spec") or {}
        obj = PersistentVolumeClaim()
        obj.storage_class_name = spec.get("storageClassName") or ""
        obj.volume_name = spec.get("volumeName", "")
    elif kind == "PersistentVolume":
        spec = d.get("spec") or {}
        obj = PersistentVolume()
        obj.driver = ((spec.get("csi") or {}).get("driver")) or ""
        obj.zones = _zones_from_node_affinity(spec.get("nodeAffinity"))
    elif kind == "StorageClass":
        obj = StorageClass()
        obj.provisioner = d.get("provisioner", "")
        for topo in d.get("allowedTopologies") or []:
            for e in topo.get("matchLabelExpressions") or []:
                if e.get("key") in (
                    "topology.kubernetes.io/zone",
                    "failure-domain.beta.kubernetes.io/zone",
                ):
                    obj.zones.extend(e.get("values") or [])
    elif kind == "CSINode":
        obj = CSINode(
            drivers=[
                CSINodeDriver(
                    name=dr.get("name", ""),
                    allocatable_count=(dr.get("allocatable") or {}).get("count"),
                )
                for dr in (d.get("spec") or {}).get("drivers") or []
            ]
        )
    elif kind == "Lease":
        spec = d.get("spec") or {}
        obj = Lease(
            holder=spec.get("holderIdentity", "") or "",
            lease_duration_seconds=spec.get("leaseDurationSeconds"),
            acquire_time=_ts(spec.get("acquireTime")),
            renew_time=_ts(spec.get("renewTime")),
            lease_transitions=spec.get("leaseTransitions", 0) or 0,
        )
    elif kind == "ConfigMap":
        obj = ConfigMap(data=dict(d.get("data") or {}))
    elif kind == "Namespace":
        obj = Namespace()
    else:
        raise ValueError(f"no decoder for kind {kind!r}")
    _meta_in(obj, d.get("metadata") or {})
    return obj


def _intstr(v):
    """Absolute int-or-string → int; PERCENT values return None so the
    consumer falls back to status.disruptionsAllowed (the PDB controller
    resolves percentages against live matching pods — this codec can't,
    and a bare number would be read as an absolute count)."""
    if v is None:
        return None
    if isinstance(v, int):
        return v
    s = str(v)
    if s.endswith("%"):
        return None
    return int(s)


# -- encoders (the kinds the controllers WRITE) -----------------------------


def to_k8s(obj: KubeObject) -> dict:
    """Encode an internal object for the apiserver. Only kinds the
    controllers create/update need full fidelity; others round-trip
    their metadata (status patches go through dedicated helpers)."""
    kind = obj.kind
    prefix, _, _ = API_PATHS[kind]
    api_version = "v1" if prefix == "/api/v1" else prefix[len("/apis/") :]
    out: dict = {
        "apiVersion": api_version,
        "kind": kind,
        "metadata": _meta_out(obj),
    }
    if kind == "NodeClaim":
        out["spec"] = {
            "taints": _taints_out(obj.spec.taints),
            "startupTaints": _taints_out(obj.spec.startup_taints),
            "requirements": [_nsr_out(r) for r in obj.spec.requirements],
            "resources": {"requests": _resources_out(obj.spec.resources.requests)},
        }
        if obj.spec.node_class_ref is not None:
            out["spec"]["nodeClassRef"] = {
                "name": obj.spec.node_class_ref.name,
                "kind": obj.spec.node_class_ref.kind,
                "apiVersion": obj.spec.node_class_ref.api_version,
            }
        if obj.spec.kubelet is not None:
            k = obj.spec.kubelet
            out["spec"]["kubelet"] = {
                key: value
                for key, value in (
                    ("maxPods", k.max_pods),
                    ("podsPerCore", k.pods_per_core),
                    ("systemReserved", _resources_out(k.system_reserved) or None),
                    ("kubeReserved", _resources_out(k.kube_reserved) or None),
                    ("evictionHard", dict(k.eviction_hard) or None),
                    ("evictionSoft", dict(k.eviction_soft) or None),
                )
                if value is not None
            }
        out["status"] = {
            "nodeName": obj.status.node_name,
            "providerID": obj.status.provider_id,
            "capacity": _resources_out(obj.status.capacity),
            "allocatable": _resources_out(obj.status.allocatable),
            "conditions": [
                {
                    "type": c.type,
                    "status": c.status,
                    "reason": c.reason,
                    "message": c.message,
                    "lastTransitionTime": _rfc3339(c.last_transition_time),
                }
                for c in obj.status.conditions
            ],
        }
    elif kind == "Node":
        out["spec"] = {
            "providerID": obj.spec.provider_id,
            "taints": _taints_out(obj.spec.taints),
            "unschedulable": obj.spec.unschedulable,
        }
    elif kind == "Lease":
        out["spec"] = {
            "holderIdentity": obj.holder,
            "leaseDurationSeconds": obj.lease_duration_seconds,
            "acquireTime": _rfc3339_micro(obj.acquire_time),
            "renewTime": _rfc3339_micro(obj.renew_time),
            "leaseTransitions": obj.lease_transitions,
        }
    elif kind == "ConfigMap":
        out["data"] = dict(obj.data)
    elif kind == "Pod":
        out["spec"] = _encode_pod_spec(obj.spec)
        out["status"] = {
            "phase": obj.status.phase,
            "conditions": [
                {"type": c.type, "status": c.status, "reason": c.reason}
                for c in obj.status.conditions
            ],
            **(
                {"startTime": _rfc3339(obj.status.start_time)}
                if obj.status.start_time is not None
                else {}
            ),
        }
    elif kind == "NodePool":
        out["spec"] = {
            "template": {
                "metadata": {
                    "labels": dict(obj.spec.template.metadata.labels),
                    "annotations": dict(obj.spec.template.metadata.annotations),
                },
                "spec": {
                    "taints": _taints_out(obj.spec.template.taints),
                    "startupTaints": _taints_out(obj.spec.template.startup_taints),
                    "requirements": [
                        _nsr_out(r) for r in obj.spec.template.requirements
                    ],
                },
            },
            "disruption": {
                "consolidationPolicy": obj.spec.disruption.consolidation_policy,
                "consolidateAfter": _duration_out(obj.spec.disruption.consolidate_after),
                "expireAfter": _duration_out(obj.spec.disruption.expire_after),
                "budgets": [
                    {
                        "nodes": b.nodes,
                        **({"schedule": b.schedule} if b.schedule else {}),
                        **(
                            {"duration": _duration_out(b.duration)}
                            if b.duration is not None
                            else {}
                        ),
                    }
                    for b in obj.spec.disruption.budgets
                ],
            },
            "limits": _resources_out(obj.spec.limits),
            **({"weight": obj.spec.weight} if obj.spec.weight is not None else {}),
        }
        out["status"] = {"resources": _resources_out(obj.status.resources)}
    return out
