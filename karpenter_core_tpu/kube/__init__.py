from .quantity import parse_quantity, format_quantity
from . import objects
