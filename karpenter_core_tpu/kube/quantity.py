"""Kubernetes resource.Quantity parsing and formatting.

The reference manipulates k8s ``resource.Quantity`` values (arbitrary
precision decimals) throughout its hot paths (ref:
pkg/utils/resources/resources.go). We canonicalize every quantity to an
integer count of **nano-units** (1 unit = 1e9 nanos): exact arithmetic
with plain Python ints, and a single fixed-point format that serializes
losslessly to the TPU tensorization layer (which rescales per resource).
"""

from __future__ import annotations

NANO = 10**9

# decimal SI suffixes → multiplier as (numerator, denominator) over base units
_DECIMAL = {
    "n": (1, 10**9),
    "u": (1, 10**6),
    "m": (1, 10**3),
    "": (1, 1),
    "k": (10**3, 1),
    "M": (10**6, 1),
    "G": (10**9, 1),
    "T": (10**12, 1),
    "P": (10**15, 1),
    "E": (10**18, 1),
}
_BINARY = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}


def parse_quantity(value) -> int:
    """Parse a k8s quantity (str | int | float) into integer nanos.

    ``parse_quantity("100m") == 100_000_000``; ``parse_quantity("1Gi") ==
    2**30 * 10**9``. Floats are supported for convenience in tests and
    the fake provider.
    """
    if isinstance(value, int):
        return value * NANO
    if isinstance(value, float):
        return round(value * NANO)
    s = value.strip()
    if not s:
        raise ValueError("empty quantity")
    neg = False
    if s[0] in "+-":
        neg = s[0] == "-"
        s = s[1:]
    # binary suffix
    for suf, mult in _BINARY.items():
        if s.endswith(suf):
            nanos = _exact(s[: -len(suf)], mult, 1)
            return -nanos if neg else nanos
    # scientific notation (k8s allows e.g. "12e6"); require a non-empty
    # integer exponent so the decimal "E" (exa) suffix doesn't match
    low = s.lower()
    if "e" in low:
        mantissa, _, exp = low.partition("e")
        if exp and (exp.lstrip("+-").isdigit()):
            e = int(exp)
            if e >= 0:
                nanos = _exact(mantissa, 10**e, 1)
            else:
                nanos = _exact(mantissa, 1, 10**-e)
            return -nanos if neg else nanos
    # decimal SI suffix
    suffix = ""
    if s and s[-1] in "numkMGTPE":
        suffix = s[-1]
        s = s[:-1]
    numer, denom = _DECIMAL[suffix]
    nanos = _exact(s, numer, denom)
    return -nanos if neg else nanos


def _exact(decimal: str, numer: int, denom: int) -> int:
    """Exact nanos for ``decimal * numer / denom`` using integer math."""
    decimal = decimal.strip()
    if not decimal:
        return 0
    if "." in decimal:
        whole, _, frac = decimal.partition(".")
        whole_i = int(whole) if whole else 0
        frac_i = int(frac) if frac else 0
        scale = 10 ** len(frac)
        return (whole_i * scale + frac_i) * numer * NANO // (denom * scale)
    return int(decimal) * numer * NANO // denom


def format_quantity(nanos: int) -> str:
    """Format nanos back into a compact k8s-style quantity string."""
    if nanos == 0:
        return "0"
    neg = "-" if nanos < 0 else ""
    nanos = abs(nanos)
    if nanos % NANO == 0:
        return f"{neg}{nanos // NANO}"
    if nanos % 10**6 == 0:
        return f"{neg}{nanos // 10**6}m"
    if nanos % 10**3 == 0:
        return f"{neg}{nanos // 10**3}u"
    return f"{neg}{nanos}n"


def to_float(nanos: int) -> float:
    """Nanos → float base units (for tensorization; may lose precision)."""
    return nanos / NANO
