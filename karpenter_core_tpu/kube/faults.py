"""Deterministic seeded fault schedules — the chaos plane's single
source of truth for "what goes wrong, when" (ISSUE 15).

The same ``FaultSchedule`` drives every consumer so a faulted run is
reproducible bit-for-bit from ``(scenario, seed)`` and the paired clean
run differs ONLY by the injected faults:

- ``RestKubeClient.fault_injector`` (kube/restclient.py) takes a
  ``RestFaultInjector`` that consults the schedule at the adapter's
  single HTTP choke point — 410 storms, stream drops, latency spikes
  against a real apiserver watch loop;
- the trafficgen harness (serving/trafficgen.py) applies the schedule
  at step boundaries over the in-memory apiserver — watch flap/hang,
  in-stream ERROR bursts, heartbeat loss, leader failover, clock skew;
- the flight recorder (tracing/flightrec.py) annotates records emitted
  inside a fault window so an SLO breach under injected chaos is
  distinguishable from an organic regression.

Host-only module: stdlib only, no jax, importable from kube/.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

# the full disruption-method coverage the tentpole names; a schedule
# may carry any subset
FAULT_KINDS: Tuple[str, ...] = (
    "relist_storm",  # apiserver 410 Gone on watch re-establishment
    "watch_flap",  # watch channel drops (connection reset) repeatedly
    "watch_hang",  # watch channel goes quiet (no events, no error)
    "error_burst",  # in-stream ERROR events (expired resourceVersion)
    "latency_spike",  # apiserver request latency, magnitude = ms
    "heartbeat_loss",  # node Ready heartbeats stop arriving
    "failover",  # leader-election failover mid-tick
    "clock_skew",  # wall clock jumps, magnitude = seconds
)


@dataclass(frozen=True)
class FaultEvent:
    """One fault window: ``kind`` active for ``duration`` consecutive
    steps starting at ``step``. ``magnitude`` is kind-specific (latency
    ms, skew seconds, burst length); 0 means the kind's default."""

    kind: str
    step: int
    duration: int = 1
    magnitude: float = 0.0

    def active_at(self, step: int) -> bool:
        return self.step <= step < self.step + max(1, self.duration)

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "step": self.step,
            "duration": self.duration,
            "magnitude": self.magnitude,
        }


class FaultSchedule:
    """An immutable, seeded list of FaultEvents addressed by step index
    (harness scenario step, or request ordinal for the REST injector)."""

    def __init__(self, name: str, seed: int, events: Sequence[FaultEvent]):
        self.name = name
        self.seed = int(seed)
        self.events: Tuple[FaultEvent, ...] = tuple(
            sorted(events, key=lambda e: (e.step, e.kind))
        )
        for ev in self.events:
            if ev.kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {ev.kind!r}")

    def active(self, step: int) -> List[FaultEvent]:
        return [e for e in self.events if e.active_at(step)]

    def kinds_at(self, step: int) -> Tuple[str, ...]:
        return tuple(e.kind for e in self.active(step))

    def first(self, kind: str) -> Optional[FaultEvent]:
        for e in self.events:
            if e.kind == kind:
                return e
        return None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "events": [e.to_dict() for e in self.events],
        }

    @staticmethod
    def build(
        name: str,
        seed: int,
        kinds: Sequence[str],
        n_steps: int,
        magnitudes: Optional[Dict[str, float]] = None,
    ) -> "FaultSchedule":
        """Deterministic schedule: one window per kind, placed by an
        rng seeded from ``(name, seed)`` alone — str-seeded Random is
        stable across processes, so the bench's subprocess runs and a
        local repro agree on every fault placement."""
        rng = random.Random(f"faultsched:{name}:{seed}")
        magnitudes = magnitudes or {}
        events: List[FaultEvent] = []
        for kind in kinds:
            if kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            # windows land in the middle half of the run: the first
            # steps establish the world, the last steps must observe
            # recovery (the bounded-divergence gate needs both)
            lo = max(1, n_steps // 4)
            hi = max(lo + 1, (3 * n_steps) // 4)
            step = rng.randrange(lo, hi)
            duration = 1 + rng.randrange(0, max(1, n_steps // 8))
            events.append(
                FaultEvent(kind, step, duration, magnitudes.get(kind, 0.0))
            )
        return FaultSchedule(name, seed, events)


class SkewClock:
    """A clock whose reading can be skewed mid-run — the clock_skew
    fault. Wraps a monotonic base so controllers injected with it keep
    their duration math monotonic between skew injections; ``skew()``
    models the wall-clock jump a bad NTP step would cause."""

    def __init__(self, base=time.monotonic, offset: float = 0.0):
        self._base = base
        self.offset = float(offset)

    def __call__(self) -> float:
        return self._base() + self.offset

    def skew(self, delta_s: float) -> None:
        self.offset += float(delta_s)


class RestFaultInjector:
    """Client-side fault injection for RestKubeClient: consulted at the
    adapter's single HTTP choke point (``_request``), addressed by
    request ordinal. Deterministic given the schedule; thread-safe
    (watch threads share one injector)."""

    def __init__(self, schedule: FaultSchedule, sleep=time.sleep):
        self.schedule = schedule
        self._sleep = sleep
        self._mu = threading.Lock()
        self._ordinal = 0
        self.injected: List[Tuple[int, str]] = []  # (ordinal, kind) log

    def __call__(self, method: str, path: str, stream: bool) -> None:
        with self._mu:
            self._ordinal += 1
            ordinal = self._ordinal
        for ev in self.schedule.active(ordinal):
            if ev.kind == "latency_spike":
                with self._mu:
                    self.injected.append((ordinal, ev.kind))
                self._sleep(max(0.0, ev.magnitude) / 1000.0)
            elif ev.kind == "relist_storm" and stream:
                # expired rv on watch re-establishment → client relists
                from .restclient import ApiError

                with self._mu:
                    self.injected.append((ordinal, ev.kind))
                raise ApiError(410, f"injected: {self.schedule.name}")
            elif ev.kind == "watch_flap" and stream:
                with self._mu:
                    self.injected.append((ordinal, ev.kind))
                raise ConnectionResetError(f"injected: {self.schedule.name}")
            elif ev.kind == "error_burst" and stream:
                # the adapter-level face of an expired-rv burst: the
                # stream request itself fails with a server error; the
                # watch loop counts it (reason="http"), backs off
                # without relisting, and resumes from the last rv. The
                # in-stream ERROR-event face is driven by the harness
                # (it owns the event channel; the injector owns the
                # request choke point).
                from .restclient import ApiError

                with self._mu:
                    self.injected.append((ordinal, ev.kind))
                raise ApiError(500, f"injected: {self.schedule.name}")
