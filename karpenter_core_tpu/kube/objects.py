"""Kubernetes-shaped object model.

The reference operates on ``k8s.io/api/core/v1`` types through
controller-runtime. We model the subset the framework needs as plain
dataclasses — pods, nodes, daemonsets, PVCs, PDBs — with the same field
semantics (owner refs, finalizers, deletion timestamps, conditions) so
the controllers translate faithfully without a kubernetes dependency.
"""

from __future__ import annotations

import itertools
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# metadata

_sequence = itertools.count(1)


def new_uid() -> str:
    return str(uuid.uuid4())


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: float = field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0
    generation: int = 1


@dataclass
class KubeObject:
    """Base for all API objects; kind is the class name."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    @property
    def kind(self) -> str:
        return type(self).__name__

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid


@dataclass
class Condition:
    """Status condition (metav1.Condition shape)."""

    type: str = ""
    status: str = "Unknown"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)


# ---------------------------------------------------------------------------
# label selectors (metav1.LabelSelector)


@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            val = labels.get(expr.key)
            if expr.operator == "In":
                if val is None or val not in expr.values:
                    return False
            elif expr.operator == "NotIn":
                if val is not None and val in expr.values:
                    return False
            elif expr.operator == "Exists":
                if val is None:
                    return False
            elif expr.operator == "DoesNotExist":
                if val is not None:
                    return False
            else:
                return False
        return True

    def key(self) -> tuple:
        """Hashable identity (for TopologyGroup dedup, ref topologygroup.go:142)."""
        return (
            tuple(sorted(self.match_labels.items())),
            tuple(sorted((e.key, e.operator, tuple(sorted(e.values))) for e in self.match_expressions)),
        )


# ---------------------------------------------------------------------------
# node selection / affinity (v1.NodeSelector et al.)

OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = OP_IN
    values: List[str] = field(default_factory=list)


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeSelector:
    node_selector_terms: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None  # requiredDuringSchedulingIgnoredDuringExecution
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    topology_key: str = ""
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)
    namespace_selector: Optional[LabelSelector] = None


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# ---------------------------------------------------------------------------
# taints / tolerations (v1.Taint, v1.Toleration; ref pkg/scheduling/taints.go)

EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = EFFECT_NO_SCHEDULE

    def match(self, other: "Taint") -> bool:
        """v1.Taint.MatchTaint: same key and effect."""
        return self.key == other.key and self.effect == other.effect


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """v1.Toleration.ToleratesTaint semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        # empty operator defaults to Equal
        return self.value == taint.value

    def match_toleration(self, other: "Toleration") -> bool:
        return (
            self.key == other.key
            and self.operator == other.operator
            and self.value == other.value
            and self.effect == other.effect
        )


# ---------------------------------------------------------------------------
# topology spread (v1.TopologySpreadConstraint)

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = DO_NOT_SCHEDULE
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None


# ---------------------------------------------------------------------------
# pods

# ResourceList: resource name → integer nanos (see kube.quantity)
ResourceList = Dict[str, int]

RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"


@dataclass
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class ResourceRequirements:
    requests: ResourceList = field(default_factory=dict)
    limits: ResourceList = field(default_factory=dict)


@dataclass
class Container:
    name: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: List[ContainerPort] = field(default_factory=list)


@dataclass
class Volume:
    name: str = ""
    persistent_volume_claim: Optional[str] = None  # claim name
    ephemeral: bool = False  # generic ephemeral volume → implicit PVC "<pod>-<vol>"


@dataclass
class PodSpec:
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: ResourceList = field(default_factory=dict)
    volumes: List[Volume] = field(default_factory=list)
    priority: Optional[int] = None
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"
    scheduler_name: str = "default-scheduler"


@dataclass
class PodCondition:
    type: str = ""
    status: str = "Unknown"
    reason: str = ""


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    conditions: List[PodCondition] = field(default_factory=list)
    start_time: Optional[float] = None


@dataclass
class Pod(KubeObject):
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)


# ---------------------------------------------------------------------------
# nodes


@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: List[Condition] = field(default_factory=list)
    phase: str = ""


@dataclass
class NodeSpec:
    provider_id: str = ""
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class Node(KubeObject):
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped


# ---------------------------------------------------------------------------
# workloads & friends (the slices controllers touch)


@dataclass
class DaemonSet(KubeObject):
    pod_template_spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class PersistentVolumeClaim(KubeObject):
    storage_class_name: Optional[str] = None
    volume_name: str = ""  # bound PV name


@dataclass
class PersistentVolume(KubeObject):
    zones: List[str] = field(default_factory=list)  # from nodeAffinity zone terms
    driver: str = ""

    def __post_init__(self):
        self.metadata.namespace = ""


@dataclass
class StorageClass(KubeObject):
    provisioner: str = ""
    zones: List[str] = field(default_factory=list)  # allowedTopologies zones

    def __post_init__(self):
        self.metadata.namespace = ""


@dataclass
class PodDisruptionBudget(KubeObject):
    selector: LabelSelector = field(default_factory=LabelSelector)
    min_available: Optional[int] = None  # absolute only (percentages resolved upstream)
    max_unavailable: Optional[int] = None
    disruptions_allowed: int = 0


@dataclass
class CSINodeDriver:
    name: str = ""
    allocatable_count: Optional[int] = None


@dataclass
class CSINode(KubeObject):
    """Per-node CSI driver registration carrying attachable-volume
    limits (storage.k8s.io/v1 CSINode; volumeusage.go hydrates limits
    from spec.drivers[].allocatable.count). Named after its Node;
    cluster-scoped, like the real resource."""

    drivers: List[CSINodeDriver] = field(default_factory=list)

    def __post_init__(self):
        self.metadata.namespace = ""


@dataclass
class ConfigMap(KubeObject):
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Namespace(KubeObject):
    def __post_init__(self):
        self.metadata.namespace = ""


@dataclass
class Lease(KubeObject):
    """coordination.k8s.io/v1 Lease spec surface: node heartbeats
    (kube-node-lease) and the leader-election resource lock."""

    holder: str = ""
    lease_duration_seconds: Optional[int] = None
    acquire_time: Optional[float] = None
    renew_time: Optional[float] = None
    lease_transitions: int = 0


# ---------------------------------------------------------------------------
# helpers


def next_name(prefix: str) -> str:
    return f"{prefix}-{next(_sequence):05d}"


def name_sequence_mark() -> int:
    """Peek the generated-name counter without consuming a name (the
    restart harness hands it to the resumed process so post-restart
    claim/node names continue the killed process's sequence)."""
    global _sequence
    mark = next(_sequence)
    _sequence = itertools.count(mark)
    return mark


def resume_name_sequence(mark: int) -> None:
    """Fast-forward the generated-name counter (never rewinds: resumed
    names must not collide with objects already in the store)."""
    global _sequence
    current = name_sequence_mark()
    _sequence = itertools.count(max(current, int(mark)))
