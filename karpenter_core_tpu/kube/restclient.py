"""Real-apiserver adapter: the in-memory KubeClient surface
(kube/client.py) implemented over the Kubernetes REST API with stdlib
HTTP only — the image carries no ``kubernetes`` client package, and the
API is plain HTTPS+JSON (ref seam: operator.go:105-171, where the
reference builds its client-go clients; envtest environment.go:80).

Usage:
    kube = RestKubeClient("https://127.0.0.1:6443", token=...)
    op = Operator(provider, kube_client=kube)

Semantics mirrored from the in-memory store:
- get/list/create/update/apply/delete/remove_finalizer/retry_on_conflict
- update() surfaces HTTP 409 as Conflict (optimistic concurrency is the
  apiserver's own resourceVersion check)
- watch(kind, cb) lists (synthetic ADDED replay, informer semantics),
  then streams ?watch=1 chunks on a daemon thread, resuming from the
  last resourceVersion; callbacks receive decoded dataclasses
- delete() is finalizer-aware by the apiserver itself (it stamps
  deletionTimestamp while finalizers remain)

The in-memory store remains the test/simulation control plane; this
adapter is for running the operator against a live cluster (kind, or
any conformant apiserver). An env-gated smoke test lives in
tests/test_restclient.py next to stub-server unit tests.
"""

from __future__ import annotations

import json
import os
import random
import ssl
import threading
import urllib.error
import urllib.request
from typing import Callable, List, Optional

from .client import ADDED, DELETED, MODIFIED, Conflict, NotFound
from .codec import API_PATHS, OBJECT_TYPES, from_k8s, to_k8s
from .objects import KubeObject, LabelSelector


class ApiError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


def _env_ms(name: str, default_ms: float) -> float:
    try:
        return float(os.environ.get(name, default_ms))
    except ValueError:
        return default_ms


class WatchBackoff:
    """Capped exponential backoff with full jitter for the relist and
    watch-error retry paths (ISSUE 15): retries are never a hot loop
    (delay is bounded below by base/2) and never unbounded (capped at
    ``KARPENTER_TPU_WATCH_BACKOFF_MAX_MS``). A healthy stream resets
    the ladder, so a one-off flap pays one base delay, not the cap."""

    def __init__(
        self,
        base_ms: Optional[float] = None,
        max_ms: Optional[float] = None,
        rng: Optional[random.Random] = None,
    ):
        if base_ms is None:
            base_ms = _env_ms("KARPENTER_TPU_WATCH_BACKOFF_BASE_MS", 200.0)
        if max_ms is None:
            max_ms = _env_ms("KARPENTER_TPU_WATCH_BACKOFF_MAX_MS", 5000.0)
        self.base_s = max(0.001, base_ms) / 1000.0
        self.max_s = max(self.base_s, max_ms / 1000.0)
        self._rng = rng or random.Random()
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def next_delay(self) -> float:
        cap = min(self.max_s, self.base_s * (2.0 ** self._attempt))
        self._attempt += 1
        return cap * (0.5 + 0.5 * self._rng.random())

    def reset(self) -> None:
        self._attempt = 0


class RestKubeClient:
    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure_skip_verify: bool = False,
        timeout: float = 30.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        if base_url.startswith("https"):
            if insecure_skip_verify:
                ctx = ssl._create_unverified_context()
            else:
                ctx = ssl.create_default_context(cafile=ca_file)
            self._ctx: Optional[ssl.SSLContext] = ctx
        else:
            self._ctx = None
        self._watch_threads: List[threading.Thread] = []
        self._streams: List = []
        self._stopping = threading.Event()
        # admission parity with the in-memory client: a real apiserver
        # runs its own webhooks, so this chain is typically empty
        self.admission: List[Callable[[KubeObject], None]] = []
        # chaos seam (ISSUE 15): an optional callable consulted before
        # every HTTP request — fault_injector(method, path, stream) may
        # sleep (latency spike) or raise (410 storm, connection reset).
        # kube/faults.py:RestFaultInjector is the seeded implementation.
        self.fault_injector: Optional[Callable[[str, str, bool], None]] = None
        # watch-loop observability, attached via attach_watch_metrics
        # (kube/ stays metrics-agnostic; the operator wiring owns the
        # registry): relists / errors / backoff-seconds counters
        self._watch_metrics: dict = {}

    def attach_watch_metrics(
        self, relists=None, errors=None, backoff_seconds=None
    ) -> None:
        """Attach the karpenter_tpu_watch_{relists,errors,
        backoff_seconds}_total counters (metrics/registry.py Metrics.
        watch_*). Safe to call any time; watch threads pick the sinks
        up on their next use."""
        self._watch_metrics = {
            "relists": relists,
            "errors": errors,
            "backoff_seconds": backoff_seconds,
        }

    def _watch_count(self, name: str, value: float = 1.0, **labels) -> None:
        sink = self._watch_metrics.get(name)
        if sink is not None:
            sink.inc(value, **labels)

    # -- plumbing ----------------------------------------------------------

    def _path(self, kind: str, namespace: str = "", name: str = "") -> str:
        prefix, plural, namespaced = API_PATHS[kind]
        path = prefix
        if namespaced and namespace:
            path += f"/namespaces/{namespace}"
        path += f"/{plural}"
        if name:
            path += f"/{name}"
        return path

    def _request(self, method: str, path: str, body: Optional[dict] = None, stream: bool = False):
        req = urllib.request.Request(
            self.base_url + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
        )
        req.add_header("Accept", "application/json")
        if body is not None:
            req.add_header(
                "Content-Type",
                "application/merge-patch+json" if method == "PATCH" else "application/json",
            )
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        inject = self.fault_injector
        if inject is not None:
            inject(method, path, stream)
        try:
            resp = urllib.request.urlopen(
                req, timeout=None if stream else self.timeout, context=self._ctx
            )
        except urllib.error.HTTPError as err:
            detail = err.read().decode("utf-8", "replace")[:400]
            if err.code == 409:
                raise Conflict(detail) from None
            if err.code == 404:
                raise NotFound(detail) from None
            raise ApiError(err.code, f"apiserver {method} {path}: {detail}") from None
        if stream:
            return resp
        with resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}

    # -- CRUD --------------------------------------------------------------

    def get(self, kind: str, name: str, namespace: str = "") -> Optional[KubeObject]:
        try:
            return from_k8s(kind, self._request("GET", self._path(kind, namespace, name)))
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[LabelSelector] = None,
        filter_fn: Optional[Callable[[KubeObject], bool]] = None,
    ) -> List[KubeObject]:
        data = self._request("GET", self._path(kind, namespace or ""))
        objs = [from_k8s(kind, item) for item in data.get("items", [])]
        if namespace is not None:
            objs = [o for o in objs if o.namespace == namespace]
        if label_selector is not None:
            objs = [o for o in objs if label_selector.matches(o.metadata.labels)]
        if filter_fn is not None:
            objs = [o for o in objs if filter_fn(o)]
        return objs

    def create(self, obj: KubeObject) -> KubeObject:
        for adm in self.admission:
            adm(obj)
        body = to_k8s(obj)
        body["metadata"].pop("resourceVersion", None)
        data = self._request("POST", self._path(obj.kind, obj.namespace), body)
        return from_k8s(obj.kind, data)

    def update(self, obj: KubeObject) -> KubeObject:
        """JSON merge-patch, not PUT: the codec encodes only the fields
        the controllers own, and a PUT would clear every server-owned
        field it omits (node podCIDR etc.). The patch body carries
        metadata.resourceVersion, so the apiserver still enforces
        optimistic concurrency (409 → Conflict). Status goes to the
        /status subresource when the kind serves one (CRDs with the
        subresource strip status from main-resource writes)."""
        for adm in self.admission:
            adm(obj)
        body = to_k8s(obj)
        status = body.pop("status", None)
        path = self._path(obj.kind, obj.namespace, obj.name)
        data = self._request("PATCH", path, body)
        if status:
            # the main patch bumped resourceVersion, so the status write
            # must be unconditional (carrying the stale rv would 409)
            status_patch = {"status": status}
            try:
                data = self._request("PATCH", path + "/status", status_patch)
            except (NotFound, ApiError):
                # no status subresource: status rides a main-resource patch
                data = self._request("PATCH", path, status_patch)
        decoded = from_k8s(obj.kind, data)
        obj.metadata.resource_version = decoded.metadata.resource_version
        return decoded

    def apply(self, obj: KubeObject) -> KubeObject:
        if self.get(obj.kind, obj.name, namespace=obj.namespace) is None:
            return self.create(obj)
        return self.update(obj)

    def delete(self, obj_or_kind, name: str = "", namespace: str = "") -> bool:
        if isinstance(obj_or_kind, KubeObject):
            kind, name, namespace = obj_or_kind.kind, obj_or_kind.name, obj_or_kind.namespace
        else:
            kind = obj_or_kind
        try:
            self._request("DELETE", self._path(kind, namespace, name))
        except NotFound:
            return False
        return True

    def remove_finalizer(self, obj: KubeObject, finalizer: str) -> None:
        def mutate(o: KubeObject) -> None:
            if finalizer in o.metadata.finalizers:
                o.metadata.finalizers.remove(finalizer)

        self.retry_on_conflict(obj.kind, obj.name, namespace=obj.namespace, mutate=mutate)

    def retry_on_conflict(
        self,
        kind: str,
        name: str,
        namespace: str = "",
        mutate: Callable[[KubeObject], None] = lambda obj: None,
        attempts: int = 5,
    ) -> KubeObject:
        last: Optional[Conflict] = None
        for _ in range(attempts):
            obj = self.get(kind, name, namespace=namespace)
            if obj is None:
                raise NotFound(f"{kind} ({namespace!r}, {name!r}) not found")
            mutate(obj)
            try:
                return self.update(obj)
            except Conflict as err:
                last = err
        raise last if last is not None else Conflict(f"{kind} {name}: retries exhausted")

    # -- watches -----------------------------------------------------------

    def watch(self, kind: str, callback: Callable[[str, KubeObject], None]) -> Callable[[], None]:
        """List + watch with synthetic ADDED replay, like the in-memory
        store (informer semantics). The stream runs on a daemon thread,
        resumes from the last seen resourceVersion, and on an expired
        version (HTTP 410 / in-stream ERROR) RE-LISTS and diffs against
        the known set — emitting DELETED for objects that vanished in
        the gap — before re-watching. Callback exceptions are isolated
        (logged, event skipped) so one bad object can't kill the stream."""
        import logging

        log = logging.getLogger("karpenter.restclient")
        known: dict = {}  # (namespace, name) -> True

        def deliver(etype: str, obj: KubeObject) -> None:
            key = (obj.namespace, obj.name)
            if etype == DELETED:
                known.pop(key, None)
            else:
                known[key] = True
            try:
                callback(etype, obj)
            except Exception:  # noqa: BLE001 — a bad object must not kill the watch
                log.exception("watch callback failed for %s %s", kind, key)

        def relist(first: bool) -> str:
            data = self._request("GET", self._path(kind))
            self._watch_count("relists", kind=kind)
            rv = (data.get("metadata") or {}).get("resourceVersion", "0")
            seen = set()
            for item in data.get("items", []):
                obj = from_k8s(kind, item)
                seen.add((obj.namespace, obj.name))
                deliver(ADDED if first or (obj.namespace, obj.name) not in known else MODIFIED, obj)
            for key in [k for k in known if k not in seen]:
                ghost = OBJECT_TYPES[kind]()
                ghost.metadata.namespace, ghost.metadata.name = key
                deliver(DELETED, ghost)
            return rv

        rv = relist(first=True)
        unsubscribed = threading.Event()
        live = {"resp": None}  # the stream unsubscribe must unblock
        backoff = WatchBackoff()

        def back_off() -> bool:
            """Sleep one capped+jittered backoff step; True → exit the
            watch thread (unsubscribed/stopping fired mid-sleep)."""
            delay = backoff.next_delay()
            self._watch_count("backoff_seconds", delay, kind=kind)
            return unsubscribed.wait(delay) or self._stopping.is_set()

        def stream():
            last_rv = rv
            while not (self._stopping.is_set() or unsubscribed.is_set()):
                try:
                    resp = self._request(
                        "GET",
                        self._path(kind)
                        + f"?watch=1&resourceVersion={last_rv}&allowWatchBookmarks=true",
                        stream=True,
                    )
                    live["resp"] = resp
                    self._streams.append(resp)
                    try:
                        for line in resp:
                            if self._stopping.is_set() or unsubscribed.is_set():
                                return
                            if not line.strip():
                                continue
                            event = json.loads(line)
                            etype = event.get("type", "")
                            item = event.get("object") or {}
                            new_rv = (item.get("metadata") or {}).get("resourceVersion")
                            if new_rv:
                                last_rv = new_rv
                            if etype == "BOOKMARK":
                                continue
                            if etype == "ERROR":
                                self._watch_count("errors", kind=kind, reason="error_event")
                                last_rv = relist(first=False)  # expired rv
                                break
                            mapped = {
                                "ADDED": ADDED,
                                "MODIFIED": MODIFIED,
                                "DELETED": DELETED,
                            }.get(etype)
                            if mapped:
                                deliver(mapped, from_k8s(kind, item))
                                backoff.reset()  # healthy stream: next error starts at base
                    finally:
                        try:
                            self._streams.remove(resp)
                            resp.close()
                        except (ValueError, OSError):
                            pass
                except ApiError as err:
                    self._watch_count(
                        "errors", kind=kind, reason="410" if err.code == 410 else "http"
                    )
                    if err.code == 410:  # Gone: event cache window passed
                        try:
                            last_rv = relist(first=False)
                        except Exception:
                            pass
                    if back_off():
                        return
                except Exception:
                    # stream dropped (network, apiserver restart): back
                    # off (capped exponential + jitter) and resume from
                    # the last seen rv
                    self._watch_count("errors", kind=kind, reason="stream")
                    if back_off():
                        return

        thread = threading.Thread(target=stream, name=f"watch-{kind}", daemon=True)
        thread.start()
        self._watch_threads.append(thread)

        def unsubscribe():
            unsubscribed.set()
            resp = live.get("resp")
            if resp is not None:
                try:
                    resp.close()  # unblock a quiet stream read immediately
                except OSError:
                    pass

        return unsubscribe

    def close(self) -> None:
        self._stopping.set()
        # unblock streams stuck in a read so their threads can exit
        for resp in list(self._streams):
            try:
                resp.close()
            except OSError:
                pass
        for thread in self._watch_threads:
            thread.join(timeout=2.0)
        self._watch_threads = []
