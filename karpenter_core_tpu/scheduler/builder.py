"""Scheduler construction: templates, per-pool instance types, topology
domain universe (ref pkg/controllers/provisioning/provisioner.go:204-296
NewScheduler). Shared by the Provisioner and tests."""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set

from ..apis.nodepool import NodePool, order_by_weight
from ..cloudprovider.types import CloudProvider, InstanceType
from ..kube.objects import OP_IN, Pod
from ..scheduling import Requirements
from ..scheduling.requirements import label_requirements, node_selector_requirements
from ..state.statenode import StateNode
from .nodeclaim import NodeClaimTemplate
from .scheduler import Scheduler, SchedulerOptions
from .topology import Topology
from .volumetopology import VolumeTopology


class NodePoolsNotFoundError(Exception):
    pass


def build_domains(nodepools_and_types) -> Dict[str, Set[str]]:
    """Topology domain universe: nodepool requirements ∩ instance-type
    requirements, so instance types can't expand beyond what the pool
    allows (provisioner.go:248-281)."""
    domains: Dict[str, Set[str]] = {}
    for nodepool, instance_types in nodepools_and_types:
        base = node_selector_requirements(nodepool.spec.template.requirements)
        base.add(*label_requirements(nodepool.spec.template.metadata.labels).values_list())
        for it in instance_types:
            requirements = Requirements(*base.copy().values_list())
            requirements.add(*it.requirements.values_list())
            for key, req in requirements.items():
                # the reference inserts raw values regardless of operator
                # (provisioner.go:257-267)
                domains.setdefault(key, set()).update(req.values)
        for key, req in base.items():
            if req.operator() == OP_IN:
                domains.setdefault(key, set()).update(req.values)
    return domains


def build_scheduler(
    kube_client,
    cluster,
    nodepools: List[NodePool],
    cloud_provider: CloudProvider,
    pods: List[Pod],
    state_nodes: Optional[List[StateNode]] = None,
    daemonset_pods: Optional[List[Pod]] = None,
    recorder=None,
    opts: Optional[SchedulerOptions] = None,
) -> Scheduler:
    nodepools = [np for np in nodepools if np.metadata.deletion_timestamp is None]
    if not nodepools:
        raise NodePoolsNotFoundError("no nodepools found")
    nodepools = order_by_weight(nodepools)

    templates: List[NodeClaimTemplate] = []
    instance_types: Dict[str, List[InstanceType]] = {}
    pool_types = []
    for np in nodepools:
        try:
            options = cloud_provider.get_instance_types(np)
        except Exception as e:  # noqa: BLE001 — one bad pool must not stop scheduling
            # (provisioner.go:236-240)
            logging.getLogger("karpenter").debug(
                "skipping nodepool %s: instance-type fetch failed: %s", np.name, e
            )
            continue
        if not options:
            continue
        templates.append(NodeClaimTemplate(np))
        instance_types.setdefault(np.name, []).extend(options)
        pool_types.append((np, options))

    domains = build_domains(pool_types)

    # register each pool's catalog with the vectorized-filter bridge once
    # per build: the catalog fingerprint check (in-place offering
    # mutation) happens here, not per filter call
    from ..solver.oracle_bridge import refresh as _bridge_refresh

    for _, options in pool_types:
        _bridge_refresh(options)

    if kube_client is not None:
        vt = VolumeTopology(kube_client)
        for p in pods:
            vt.inject(p)

    topology = Topology(kube_client, cluster, domains, pods)
    return Scheduler(
        kube_client,
        templates,
        nodepools,
        cluster,
        state_nodes or [],
        topology,
        instance_types,
        daemonset_pods or [],
        recorder,
        opts,
    )
