"""Volume topology injection: rewrite pod node-affinity with zone
requirements from bound/dynamic PVCs (ref
pkg/controllers/provisioning/scheduling/volumetopology.go)."""

from __future__ import annotations

from typing import List, Optional

from ..apis import labels as wk
from ..kube.objects import (
    Affinity,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    OP_IN,
    Pod,
)


class VolumeTopology:
    def __init__(self, kube_client):
        self.kube_client = kube_client

    def inject(self, pod: Pod) -> None:
        """Add zone requirements from the pod's PVCs into every required
        node-affinity term (volumetopology.go:42 Inject)."""
        requirements: List[NodeSelectorRequirement] = []
        for volume in pod.spec.volumes:
            reqs = self._requirements_for_volume(pod, volume)
            requirements.extend(reqs)
        if not requirements:
            return
        if pod.spec.affinity is None:
            pod.spec.affinity = Affinity()
        if pod.spec.affinity.node_affinity is None:
            pod.spec.affinity.node_affinity = NodeAffinity()
        na = pod.spec.affinity.node_affinity
        if na.required is None:
            na.required = NodeSelector()
        if not na.required.node_selector_terms:
            na.required.node_selector_terms = [NodeSelectorTerm()]
        # zone requirements apply to every OR'd term (volumetopology.go:66-76)
        for term in na.required.node_selector_terms:
            term.match_expressions = term.match_expressions + requirements
        # in-place spec mutation without a resource_version bump: drop the
        # pod's scheduling memo (solver.podcache invariant) so signature
        # grouping sees the injected zone affinity
        pod.__dict__.pop("_karp_memo", None)

    def _requirements_for_volume(self, pod: Pod, volume) -> List[NodeSelectorRequirement]:
        if volume.persistent_volume_claim:
            pvc = self.kube_client.get(
                "PersistentVolumeClaim", volume.persistent_volume_claim, namespace=pod.namespace
            )
            if pvc is None:
                return []
            # bound PV zones win; else storage class allowed topologies
            if pvc.volume_name:
                pv = self.kube_client.get("PersistentVolume", pvc.volume_name)
                if pv is not None and pv.zones:
                    return [NodeSelectorRequirement(wk.LABEL_TOPOLOGY_ZONE, OP_IN, list(pv.zones))]
            if pvc.storage_class_name:
                sc = self.kube_client.get("StorageClass", pvc.storage_class_name)
                if sc is not None and sc.zones:
                    return [NodeSelectorRequirement(wk.LABEL_TOPOLOGY_ZONE, OP_IN, list(sc.zones))]
        return []

    def validate_persistent_volume_claims(self, pod: Pod) -> Optional[str]:
        """Error if a referenced PVC — explicit, or the implicit
        ``<pod>-<volume>`` of a generic ephemeral volume — doesn't exist,
        or an unbound PVC names a storage class that doesn't
        (volumetopology.go:160-190 ValidatePersistentVolumeClaims +
        validateStorageClass)."""
        for volume in pod.spec.volumes:
            if volume.persistent_volume_claim:
                pvc_name = volume.persistent_volume_claim
            elif volume.ephemeral:
                pvc_name = f"{pod.metadata.name}-{volume.name}"
            else:
                continue
            pvc = self.kube_client.get(
                "PersistentVolumeClaim", pvc_name, namespace=pod.namespace
            )
            if pvc is None:
                if volume.ephemeral:
                    continue  # implicit PVC not created yet: nothing to validate
                return f'configuring volume "{volume.name}", unable to find persistent volume claim "{pvc_name}"'
            # an unbound claim's storage class must resolve, or the node
            # we launch can never satisfy the volume
            if not pvc.volume_name and pvc.storage_class_name:
                sc = self.kube_client.get("StorageClass", pvc.storage_class_name)
                if sc is None:
                    return (
                        f'configuring volume "{volume.name}", unable to find '
                        f'storage class "{pvc.storage_class_name}"'
                    )
        return None
