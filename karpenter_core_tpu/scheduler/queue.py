"""Progress-detecting scheduling queue (ref
pkg/controllers/provisioning/scheduling/queue.go)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..kube.objects import Pod
from ..scheduling import resources


def _sort_key(pod: Pod) -> tuple:
    """CPU then memory descending; creation time + UID for stable ordering
    (queue.go:76 byCPUAndMemoryDescending)."""
    requests = resources.requests_for_pods(pod)
    return (
        -requests.get("cpu", 0),
        -requests.get("memory", 0),
        pod.metadata.creation_timestamp,
        pod.metadata.uid,
    )


class Queue:
    """Pops pods while progress is being made; a pod re-pushed un-relaxed at
    an unchanged queue length means we've cycled without progress
    (queue.go:46-70)."""

    def __init__(self, *pods: Pod):
        self.pods: List[Pod] = sorted(pods, key=_sort_key)
        self.last_len: Dict[str, int] = {}

    def pop(self) -> Tuple[Optional[Pod], bool]:
        if not self.pods:
            return None, False
        pod = self.pods[0]
        if self.last_len.get(pod.uid) == len(self.pods):
            return None, False
        self.pods.pop(0)
        return pod, True

    def push(self, pod: Pod, relaxed: bool) -> None:
        self.pods.append(pod)
        if relaxed:
            self.last_len = {}
        else:
            self.last_len[pod.uid] = len(self.pods)

    def list(self) -> List[Pod]:
        return list(self.pods)
