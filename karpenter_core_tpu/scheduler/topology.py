"""Topology engine: spread constraints, pod affinity/anti-affinity
(ref pkg/controllers/provisioning/scheduling/topology.go,
topologygroup.go, topologynodefilter.go).

Domain counts per TopologyGroup are the state the TPU path tensorizes:
each group is a row of int32 counters over its domain universe, min-skew
domain selection is an argmin-reduce (see solver.topology_kernels).
"""

from __future__ import annotations

import math
from typing import AbstractSet, Callable, Dict, List, Optional, Set, Tuple

from ..apis import labels as wk
from ..kube.objects import (
    LabelSelector,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    Pod,
)
from ..scheduling import Requirement, Requirements
from ..scheduling.requirements import label_requirements, node_selector_requirements
from ..utils import pod as podutils

TOPOLOGY_TYPE_SPREAD = "topology spread"
TOPOLOGY_TYPE_POD_AFFINITY = "pod affinity"
TOPOLOGY_TYPE_POD_ANTI_AFFINITY = "pod anti-affinity"

MAX_INT32 = (1 << 31) - 1


class TopologyNodeFilter:
    """OR of requirement sets restricting which nodes count for a spread
    (topologynodefilter.go:31). Empty filter matches everything."""

    def __init__(self, requirements: Optional[List[Requirements]] = None):
        self.requirements = requirements or []

    @classmethod
    def for_pod(cls, pod: Pod) -> "TopologyNodeFilter":
        selector_reqs = label_requirements(pod.spec.node_selector)
        a = pod.spec.affinity
        if a is None or a.node_affinity is None or a.node_affinity.required is None:
            return cls([selector_reqs])
        filters = []
        for term in a.node_affinity.required.node_selector_terms:
            reqs = Requirements()
            reqs.add(*selector_reqs.values_list())
            reqs.add(*node_selector_requirements(term.match_expressions).values_list())
            filters.append(reqs)
        return cls(filters)

    def matches_labels(self, labels: Dict[str, str]) -> bool:
        return self.matches_requirements(label_requirements(labels))

    def matches_requirements(
        self, requirements: Requirements, allow_undefined: AbstractSet[str] = frozenset()
    ) -> bool:
        if not self.requirements:
            return True
        return any(requirements.compatible(req, allow_undefined, hint=False) is None for req in self.requirements)

    def key(self) -> tuple:
        return tuple(
            tuple(sorted((k, r.complement, tuple(sorted(r.values)), r.greater_than, r.less_than) for k, r in reqs.items()))
            for reqs in self.requirements
        )


class TopologyGroup:
    """Pod counts per domain for one constraint (topologygroup.go:56)."""

    def __init__(
        self,
        topology_type: str,
        key: str,
        pod: Optional[Pod],
        namespaces: Set[str],
        selector: Optional[LabelSelector],
        max_skew: int,
        min_domains: Optional[int],
        domains: Set[str],
    ):
        self.type = topology_type
        self.key = key
        self.namespaces = set(namespaces)
        self.selector = selector
        self.max_skew = max_skew
        self.min_domains = min_domains
        self.domains: Dict[str, int] = {d: 0 for d in domains}
        self.owners: Set[str] = set()  # pod UIDs governed by this group
        self.node_filter = (
            TopologyNodeFilter.for_pod(pod)
            if topology_type == TOPOLOGY_TYPE_SPREAD and pod is not None
            else TopologyNodeFilter()
        )

    # -- identity (topologygroup.go:142 Hash) ------------------------------

    def hash_key(self) -> tuple:
        return (
            self.type,
            self.key,
            frozenset(self.namespaces),
            self.selector.key() if self.selector else None,
            self.max_skew,
            self.node_filter.key(),
        )

    # -- domain selection (topologygroup.go:93 Get) ------------------------

    def get(self, pod: Pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        if self.type == TOPOLOGY_TYPE_SPREAD:
            return self._next_domain_spread(pod, pod_domains, node_domains)
        if self.type == TOPOLOGY_TYPE_POD_AFFINITY:
            return self._next_domain_affinity(pod, pod_domains, node_domains)
        return self._next_domain_anti_affinity(pod_domains)

    def record(self, *domains: str) -> None:
        for d in domains:
            self.domains[d] = self.domains.get(d, 0) + 1

    def counts(
        self, pod: Pod, requirements: Requirements, allow_undefined: AbstractSet[str] = frozenset()
    ) -> bool:
        """Would this pod count against the group on a node with these
        requirements? (topologygroup.go:114)"""
        return self.selects(pod) and self.node_filter.matches_requirements(
            requirements, allow_undefined
        )

    def register(self, *domains: str) -> None:
        for d in domains:
            self.domains.setdefault(d, 0)

    def add_owner(self, uid: str) -> None:
        self.owners.add(uid)

    def remove_owner(self, uid: str) -> None:
        self.owners.discard(uid)

    def is_owned_by(self, uid: str) -> bool:
        return uid in self.owners

    def selects(self, pod: Pod) -> bool:
        if pod.namespace not in self.namespaces:
            return False
        if self.selector is None:
            # nil LabelSelector selects nothing in metav1 semantics...
            # except the reference builds groups from the pod's own
            # constraints where nil selector matches nothing
            return False
        return self.selector.matches(pod.metadata.labels)

    # -- internals ---------------------------------------------------------

    def _next_domain_spread(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """Min-count domain within maxSkew of the global min
        (topologygroup.go:163)."""
        min_count = self._domain_min_count(pod_domains)
        self_selecting = self.selects(pod)
        min_domain = None
        min_domain_count = MAX_INT32
        for domain, count in self.domains.items():
            if node_domains.has(domain):
                if self_selecting:
                    count += 1
                if count - min_count <= self.max_skew and count < min_domain_count:
                    min_domain = domain
                    min_domain_count = count
        if min_domain is None:
            return Requirement(self.key, OP_DOES_NOT_EXIST)
        return Requirement(self.key, OP_IN, [min_domain])

    def _domain_min_count(self, domains: Requirement) -> int:
        """Global min count over pod-supported domains; hostname topologies
        have min 0 (we can always create a node) (topologygroup.go:192)."""
        if self.key == wk.LABEL_HOSTNAME:
            return 0
        min_count = MAX_INT32
        supported = 0
        for domain, count in self.domains.items():
            if domains.has(domain):
                supported += 1
                if count < min_count:
                    min_count = count
        if self.min_domains is not None and supported < self.min_domains:
            min_count = 0
        return min_count

    def _next_domain_affinity(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """Domains already holding a matching pod; bootstrap for
        self-selecting pods (topologygroup.go:215)."""
        options = Requirement(self.key, OP_DOES_NOT_EXIST)
        for domain, count in self.domains.items():
            if pod_domains.has(domain) and count > 0:
                options.insert(domain)
        if options.len() == 0 and self.selects(pod):
            intersected = pod_domains.intersection(node_domains)
            for domain in self.domains:
                if intersected.has(domain):
                    options.insert(domain)
                    break
            for domain in self.domains:
                if pod_domains.has(domain):
                    options.insert(domain)
                    break
        return options

    def _next_domain_anti_affinity(self, domains: Requirement) -> Requirement:
        """Domains with zero matching pods (topologygroup.go:248)."""
        options = Requirement(self.key, OP_DOES_NOT_EXIST)
        for domain, count in self.domains.items():
            if domains.has(domain) and count == 0:
                options.insert(domain)
        return options

    def admissible_domains(self, pod: Pod, pod_domains: Requirement) -> Optional[Set[str]]:
        """The single-valued node domains {d} for which get() would return
        a non-empty requirement — i.e. the claims this group could accept
        the pod on, as a function of per-domain counts only. Returns None
        when the outcome is not claim-independent (affinity bootstrap,
        where get() may offer a domain outside node_domains)."""
        if self.type == TOPOLOGY_TYPE_SPREAD:
            min_count = self._domain_min_count(pod_domains)
            bump = 1 if self.selects(pod) else 0
            return {
                d
                for d, c in self.domains.items()
                if (c + bump) - min_count <= self.max_skew
            }
        if self.type == TOPOLOGY_TYPE_POD_AFFINITY:
            anchored = {
                d for d, c in self.domains.items() if c > 0 and pod_domains.has(d)
            }
            if anchored:
                return anchored
            if self.selects(pod):
                return None  # bootstrap: get() falls back past node_domains
            return set()
        return {d for d, c in self.domains.items() if c == 0 and pod_domains.has(d)}


def _ignored_for_topology(p: Pod) -> bool:
    return not podutils.is_scheduled(p) or podutils.is_terminal(p) or podutils.is_terminating(p)


def count_matching_pods_by_domain(
    kube_client, tg: TopologyGroup, excluded_uids
) -> Dict[str, int]:
    """Per-domain count of existing pods matching a topology group's
    selector/namespaces/node filter (topology.go:238 countDomains).
    Shared by the oracle's seeding and the tensor path's
    (solver/topology_tensor.py) so the two can't drift."""
    counts: Dict[str, int] = {}
    if kube_client is None:
        return counts
    pods: List[Pod] = []
    for ns in tg.namespaces:
        pods.extend(
            kube_client.list(
                "Pod", namespace=ns, label_selector=tg.selector or LabelSelector()
            )
        )
    for p in pods:
        if _ignored_for_topology(p) or p.uid in excluded_uids:
            continue
        node = kube_client.get("Node", p.spec.node_name)
        if node is None:
            continue
        domain = node.metadata.labels.get(tg.key)
        if domain is None and tg.key == wk.LABEL_HOSTNAME:
            # node may not be labeled yet; fall back to node name
            # (topology.go:272-279)
            domain = node.name
        if domain is None:
            continue
        if not tg.node_filter.matches_labels(node.metadata.labels):
            continue
        counts[domain] = counts.get(domain, 0) + 1
    return counts


class Topology:
    """All topology groups for one scheduling batch (topology.go:42)."""

    def __init__(
        self,
        kube_client,
        cluster,
        domains: Dict[str, Set[str]],
        pods: List[Pod],
    ):
        self.kube_client = kube_client
        self.cluster = cluster
        self.domain_universe = domains
        self.topologies: Dict[tuple, TopologyGroup] = {}
        self.inverse_topologies: Dict[tuple, TopologyGroup] = {}
        self._owner_index: Dict[str, List[TopologyGroup]] = {}
        # (namespace, labels) → groups selecting such pods, invalidated
        # by generation when a group is registered: record() runs per
        # landed pod and a full selector scan there dominated profiles
        self._select_cache: Dict[tuple, Tuple[int, List[TopologyGroup]]] = {}
        self._groups_generation = 0
        # pods being scheduled don't count against existing topologies
        # (topology.go:71-75)
        self.excluded_pods: Set[str] = {p.uid for p in pods}
        self._update_inverse_affinities()
        for p in pods:
            self.update(p)

    # -- group registration ------------------------------------------------

    def update(self, pod: Pod) -> None:
        """(Re)register the pod as owner of its constraint groups; called
        after relaxation to drop stale ownership (topology.go:91)."""
        # ownership only ever lands via this method, which also indexes
        # it — so the index is a complete view for removal
        for tg in self._owner_index.get(pod.uid, ()):
            tg.remove_owner(pod.uid)

        if podutils.has_pod_anti_affinity(pod):
            self._update_inverse_anti_affinity(pod, None)

        groups = self._new_for_topologies(pod) + self._new_for_affinities(pod)
        # dedup by hash key: two of a pod's terms can hash to the same
        # group (e.g. identical required+preferred affinity terms), and
        # the old full-dict scan naturally returned each group once
        owned: Dict[tuple, TopologyGroup] = {}
        for tg in groups:
            key = tg.hash_key()
            existing = self.topologies.get(key)
            if existing is None:
                self._count_domains(tg)
                self.topologies[key] = tg
                self._groups_generation += 1
            else:
                tg = existing
            tg.add_owner(pod.uid)
            owned[key] = tg
        # pod → owned groups index: _matching_topologies runs per
        # pod-per-claim attempt, and a full scan of every group there
        # dominated the diverse-mix profile
        self._owner_index[pod.uid] = list(owned.values())

    def _groups_selecting(self, pod: Pod) -> List[TopologyGroup]:
        """Groups whose selector/namespaces match the pod, cached per
        (namespace, labels) — selects() depends on nothing else."""
        key = (pod.namespace, tuple(sorted(pod.metadata.labels.items())))
        hit = self._select_cache.get(key)
        if hit is not None and hit[0] == self._groups_generation:
            return hit[1]
        out = [tg for tg in self.topologies.values() if tg.selects(pod)]
        if len(self._select_cache) > 4096:
            self._select_cache.clear()
        self._select_cache[key] = (self._groups_generation, out)
        return out

    def record(
        self, pod: Pod, requirements: Requirements, allow_undefined: AbstractSet[str] = frozenset()
    ) -> None:
        """Commit domain counts once the pod lands (topology.go:125)."""
        for tg in self._groups_selecting(pod):
            if tg.node_filter.matches_requirements(requirements, allow_undefined):
                domains = requirements.get_req(tg.key)
                if tg.type == TOPOLOGY_TYPE_POD_ANTI_AFFINITY:
                    tg.record(*sorted(domains.values))
                elif domains.len() == 1:
                    tg.record(next(iter(domains.values)))
        for tg in self.inverse_topologies.values():
            if tg.is_owned_by(pod.uid):
                tg.record(*sorted(requirements.get_req(tg.key).values))

    def add_requirements(
        self,
        pod_requirements: Requirements,
        node_requirements: Requirements,
        pod: Pod,
        allow_undefined: AbstractSet[str] = frozenset(),
    ) -> Requirements:
        """Tighten node requirements to topology-admissible domains; raises
        on unsatisfiable (topology.go:154)."""
        requirements = Requirements(*node_requirements.values_list())
        for tg in self._matching_topologies(pod, node_requirements, allow_undefined):
            pod_domains = pod_requirements.get_req(tg.key)
            node_domains = node_requirements.get_req(tg.key)
            domains = tg.get(pod, pod_domains, node_domains)
            if domains.len() == 0:
                raise TopologyError(
                    f"unsatisfiable topology constraint for {tg.type}, key={tg.key} "
                    f"(counts = {tg.domains}, podDomains = {pod_domains!r}, "
                    f"nodeDomains = {node_domains!r})"
                )
            requirements.add(domains)
        return requirements

    def register(self, topology_key: str, domain: str) -> None:
        """Make a new domain (e.g. a new hostname) known (topology.go:175)."""
        for tg in self.topologies.values():
            if tg.key == topology_key:
                tg.register(domain)
        for tg in self.inverse_topologies.values():
            if tg.key == topology_key:
                tg.register(domain)

    def admissible_by_key(
        self, pod: Pod, pod_requirements: Requirements
    ) -> Optional[Dict[str, Set[str]]]:
        """Per topology key, the domain values on which some claim could
        still accept this pod; None when no group constrains it claim-
        independently. get()'s per-claim outcome depends only on
        per-domain counts and the claim's value set for the key, so the
        scheduler's claim loop computes this once per pod and skips
        claims whose concrete values are disjoint from the admissible
        set, instead of paying add()'s requirement/topology machinery
        per doomed attempt (which dominated the diverse-mix profile)."""
        result: Optional[Dict[str, Set[str]]] = None

        def fold(tg: TopologyGroup) -> None:
            nonlocal result
            dom = tg.admissible_domains(pod, pod_requirements.get_req(tg.key))
            if dom is None:
                return
            if result is None:
                result = {tg.key: dom}
            elif tg.key in result:
                result[tg.key] &= dom
            else:
                result[tg.key] = dom

        for tg in self._owner_index.get(pod.uid, ()):
            fold(tg)
        # inverse groups are always anti-affinity with an empty node
        # filter (built that way in _update_inverse_anti_affinity), so
        # their per-claim membership reduces to selects(pod)
        for tg in self.inverse_topologies.values():
            if tg.selects(pod):
                fold(tg)
        return result

    # -- internals ---------------------------------------------------------

    def _update_inverse_affinities(self) -> None:
        """Track existing pods with anti-affinity: they block domains for
        pods they select (topology.go:190)."""
        if self.cluster is None:
            return

        def visit(pod: Pod, node) -> bool:
            if pod.uid not in self.excluded_pods:
                self._update_inverse_anti_affinity(
                    pod, node.metadata.labels if node is not None else None
                )
            return True

        self.cluster.for_pods_with_anti_affinity(visit)

    def _update_inverse_anti_affinity(self, pod: Pod, node_labels: Optional[Dict[str, str]]) -> None:
        """Only required inverse anti-affinities are tracked
        (topology.go:207)."""
        assert pod.spec.affinity and pod.spec.affinity.pod_anti_affinity
        for term in pod.spec.affinity.pod_anti_affinity.required:
            namespaces = self._build_namespace_list(pod.namespace, term.namespaces, term.namespace_selector)
            tg = TopologyGroup(
                TOPOLOGY_TYPE_POD_ANTI_AFFINITY,
                term.topology_key,
                pod,
                namespaces,
                term.label_selector,
                MAX_INT32,
                None,
                self.domain_universe.get(term.topology_key, set()),
            )
            key = tg.hash_key()
            existing = self.inverse_topologies.get(key)
            if existing is None:
                self.inverse_topologies[key] = tg
            else:
                tg = existing
            if node_labels and tg.key in node_labels:
                tg.record(node_labels[tg.key])
            tg.add_owner(pod.uid)

    def _count_domains(self, tg: TopologyGroup) -> None:
        """Count existing matching pods into the group (topology.go:238)."""
        for domain, n in count_matching_pods_by_domain(
            self.kube_client, tg, self.excluded_pods
        ).items():
            tg.domains[domain] = tg.domains.get(domain, 0) + n

    def _new_for_topologies(self, p: Pod) -> List[TopologyGroup]:
        groups = []
        for cs in p.spec.topology_spread_constraints:
            groups.append(
                TopologyGroup(
                    TOPOLOGY_TYPE_SPREAD,
                    cs.topology_key,
                    p,
                    {p.namespace},
                    cs.label_selector,
                    cs.max_skew,
                    cs.min_domains,
                    self.domain_universe.get(cs.topology_key, set()),
                )
            )
        return groups

    def _new_for_affinities(self, p: Pod) -> List[TopologyGroup]:
        """Both hard and soft affinity terms become groups; soft ones are
        dropped via relaxation (topology.go:302)."""
        groups = []
        a = p.spec.affinity
        if a is None:
            return groups
        terms: List[Tuple[str, object]] = []
        if a.pod_affinity is not None:
            terms += [(TOPOLOGY_TYPE_POD_AFFINITY, t) for t in a.pod_affinity.required]
            terms += [(TOPOLOGY_TYPE_POD_AFFINITY, t.pod_affinity_term) for t in a.pod_affinity.preferred]
        if a.pod_anti_affinity is not None:
            terms += [(TOPOLOGY_TYPE_POD_ANTI_AFFINITY, t) for t in a.pod_anti_affinity.required]
            terms += [
                (TOPOLOGY_TYPE_POD_ANTI_AFFINITY, t.pod_affinity_term)
                for t in a.pod_anti_affinity.preferred
            ]
        for topology_type, term in terms:
            namespaces = self._build_namespace_list(p.namespace, term.namespaces, term.namespace_selector)
            groups.append(
                TopologyGroup(
                    topology_type,
                    term.topology_key,
                    p,
                    namespaces,
                    term.label_selector,
                    MAX_INT32,
                    None,
                    self.domain_universe.get(term.topology_key, set()),
                )
            )
        return groups

    def _build_namespace_list(
        self, namespace: str, namespaces: List[str], selector: Optional[LabelSelector]
    ) -> Set[str]:
        """Pod's namespace + listed + selected (topology.go:341)."""
        if not namespaces and selector is None:
            return {namespace}
        if selector is None:
            return set(namespaces)
        selected = set(namespaces)
        if self.kube_client is not None:
            for ns in self.kube_client.list("Namespace", label_selector=selector):
                selected.add(ns.name)
        return selected

    def _matching_topologies(
        self, p: Pod, requirements: Requirements, allow_undefined: AbstractSet[str]
    ) -> List[TopologyGroup]:
        """Groups owning p (indexed — update() maintains it), plus
        inverse groups selecting p (topology.go:366)."""
        matching = list(self._owner_index.get(p.uid, ()))
        matching += [
            tg
            for tg in self.inverse_topologies.values()
            if tg.counts(p, requirements, allow_undefined)
        ]
        return matching


class TopologyError(Exception):
    pass
