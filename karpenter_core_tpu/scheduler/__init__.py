from .scheduler import Scheduler, SchedulerOptions, Results
from .nodeclaim import NodeClaimTemplate, SchedulingNodeClaim
from .existingnode import ExistingNode
from .topology import Topology, TopologyGroup
from .queue import Queue
from .preferences import Preferences
