"""Scheduling against in-flight/existing nodes (ref
pkg/controllers/provisioning/scheduling/existingnode.go)."""

from __future__ import annotations

from typing import List, Optional

from ..apis import labels as wk
from ..kube.objects import OP_IN, Pod, ResourceList
from ..scheduling import Requirement, Requirements, Taints, resources
from ..scheduling.hostports import get_host_ports
from ..scheduling.requirements import (
    has_preferred_node_affinity,
    label_requirements,
    pod_requirements,
    strict_pod_requirements,
)
from ..scheduling.volumes import get_volumes
from ..state.statenode import StateNode
from .topology import Topology, TopologyError


class ExistingNode:
    """A deep-copied StateNode being packed during scheduling
    (existingnode.go:31)."""

    def __init__(self, state_node: StateNode, topology: Topology, daemon_resources: ResourceList):
        self.state_node = state_node
        self.topology = topology
        self.pods: List[Pod] = []
        # remaining daemon resources = expected total minus already scheduled,
        # floored at zero (existingnode.go:43-52)
        remaining = resources.subtract(daemon_resources, state_node.daemonset_request_total())
        self.requests = {k: max(v, 0) for k, v in remaining.items()}
        self.requirements = label_requirements(state_node.labels())
        hostname = state_node.hostname()
        self.requirements.add(Requirement(wk.LABEL_HOSTNAME, OP_IN, [hostname]))
        topology.register(wk.LABEL_HOSTNAME, hostname)

    # pass-throughs
    def name(self) -> str:
        return self.state_node.name()

    def provider_id(self) -> str:
        return self.state_node.provider_id()

    def initialized(self) -> bool:
        return self.state_node.initialized()

    def add(self, kube_client, pod: Pod) -> Optional[str]:
        """Try to place the pod on this node (existingnode.go:64)."""
        err = Taints(self.state_node.taints()).tolerates(pod)
        if err:
            return err
        try:
            volumes = get_volumes(kube_client, pod) if kube_client is not None else None
        except KeyError as e:
            return str(e)
        host_ports = get_host_ports(pod)
        if volumes is not None:
            err = self.state_node.volume_usage.exceeds_limits(volumes)
            if err:
                return f"checking volume usage, {err}"
        err = self.state_node.host_port_usage.conflicts(pod, host_ports)
        if err:
            return f"checking host port usage, {err}"

        # resources first: in-flight nodes can't grow (existingnode.go:83)
        requests = resources.merge(self.requests, resources.requests_for_pods(pod))
        if not resources.fits(requests, self.state_node.available()):
            return "exceeds node resources"

        node_requirements = Requirements(*self.requirements.values_list())
        pod_reqs = pod_requirements(pod)
        err = node_requirements.compatible(pod_reqs)
        if err:
            return err
        node_requirements.add(*pod_reqs.values_list())

        strict_reqs = pod_reqs
        if has_preferred_node_affinity(pod):
            strict_reqs = strict_pod_requirements(pod)

        try:
            topology_requirements = self.topology.add_requirements(strict_reqs, node_requirements, pod)
        except TopologyError as e:
            return str(e)
        err = node_requirements.compatible(topology_requirements)
        if err:
            return err
        node_requirements.add(*topology_requirements.values_list())

        # commit
        self.pods.append(pod)
        self.requests = requests
        self.requirements = node_requirements
        self.topology.record(pod, node_requirements)
        self.state_node.host_port_usage.add(pod, host_ports)
        if volumes is not None:
            self.state_node.volume_usage.add(pod, volumes)
        return None
