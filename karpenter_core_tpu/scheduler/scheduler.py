"""The greedy CPU oracle scheduler (ref
pkg/controllers/provisioning/scheduling/scheduler.go).

This is the correctness oracle for the TPU solver: bit-faithful
semantics of the reference's per-pod loop. The TPU path
(``karpenter_core_tpu.solver``) must match its packing metrics (node
count / cost / feasibility) to ≥99%; it falls back to this path for
relational constraints it can't tensorize yet.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..apis import labels as wk
from ..apis.nodepool import NodePool
from ..cloudprovider.types import InstanceType
from ..kube.objects import EFFECT_PREFER_NO_SCHEDULE, Pod, ResourceList
from ..scheduling import Taints, resources
from ..scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    has_preferred_node_affinity,
    label_requirements,
    pod_requirements,
    strict_pod_requirements,
)
from ..state.statenode import StateNode
from ..utils import pod as podutils
from .existingnode import ExistingNode
from .nodeclaim import NodeClaimTemplate, SchedulingNodeClaim
from .preferences import Preferences
from .queue import Queue
from .topology import Topology


@dataclass
class SchedulerOptions:
    """simulation_mode suppresses nomination events/logging during
    consolidation simulation (scheduler.go:44)."""

    simulation_mode: bool = False


@dataclass
class Results:
    """Scheduling outcome (scheduler.go:102)."""

    new_node_claims: List[SchedulingNodeClaim] = field(default_factory=list)
    existing_nodes: List[ExistingNode] = field(default_factory=list)
    pod_errors: Dict[str, str] = field(default_factory=dict)  # pod uid → error
    _pods_by_uid: Dict[str, Pod] = field(default_factory=dict)

    def all_non_pending_pods_scheduled(self) -> bool:
        """Pods that were already pending before simulation don't block
        consolidation (scheduler.go:111)."""
        return not [
            uid
            for uid, err in self.pod_errors.items()
            if not podutils.is_provisionable(self._pods_by_uid[uid])
        ]

    def non_pending_pod_scheduling_errors(self) -> str:
        errs = {
            uid: err
            for uid, err in self.pod_errors.items()
            if not podutils.is_provisionable(self._pods_by_uid[uid])
        }
        if not errs:
            return "No Pod Scheduling Errors"
        parts = []
        for uid, err in list(errs.items())[:5]:
            p = self._pods_by_uid[uid]
            parts.append(f"{p.namespace}/{p.name} => {err}")
        msg = "not all pods would schedule, " + " ".join(parts)
        if len(errs) > 5:
            msg += f" and {len(errs) - 5} other(s)"
        return msg


class Scheduler:
    """scheduler.go:49 NewScheduler + Solve."""

    def __init__(
        self,
        kube_client,
        node_claim_templates: List[NodeClaimTemplate],
        nodepools: List[NodePool],
        cluster,
        state_nodes: List[StateNode],
        topology: Topology,
        instance_types: Dict[str, List[InstanceType]],
        daemonset_pods: List[Pod],
        recorder=None,
        opts: Optional[SchedulerOptions] = None,
    ):
        self.kube_client = kube_client
        self.node_claim_templates = node_claim_templates
        self.topology = topology
        self.cluster = cluster
        self.instance_types = instance_types
        self.recorder = recorder
        self.opts = opts or SchedulerOptions()
        self.new_node_claims: List[SchedulingNodeClaim] = []
        self.existing_nodes: List[ExistingNode] = []

        # any NodePool with a PreferNoSchedule taint enables the matching
        # relaxation (scheduler.go:54-63)
        tolerate_prefer_no_schedule = any(
            t.effect == EFFECT_PREFER_NO_SCHEDULE
            for np in nodepools
            for t in np.spec.template.taints
        )
        self.preferences = Preferences(tolerate_prefer_no_schedule)

        self.nodepools = {np.name: np for np in nodepools}
        # NodePool limits tracked pessimistically (scheduler.go:76-80)
        self.remaining_resources: Dict[str, ResourceList] = {
            np.name: dict(np.spec.limits) for np in nodepools if np.spec.limits
        }
        self.daemon_overhead = _daemon_overhead(node_claim_templates, daemonset_pods)
        self._calculate_existing_node_claims(state_nodes, daemonset_pods)

    # -- solve (scheduler.go:140) ------------------------------------------

    def solve(self, pods: List[Pod]) -> Results:
        errors: Dict[str, str] = {}
        pods_by_uid = {p.uid: p for p in pods}
        q = Queue(*pods)
        while True:
            pod, ok = q.pop()
            if not ok:
                break
            err = self._add(pod)
            errors[pod.uid] = err
            if err is None:
                continue
            relaxed = self.preferences.relax(pod)
            q.push(pod, relaxed)
            if relaxed:
                self.topology.update(pod)

        for claim in self.new_node_claims:
            claim.finalize_scheduling()
        if not self.opts.simulation_mode:
            self._record_results(pods_by_uid, q.list(), errors)
        errors = {uid: e for uid, e in errors.items() if e is not None}
        return Results(
            new_node_claims=self.new_node_claims,
            existing_nodes=self.existing_nodes,
            pod_errors=errors,
            _pods_by_uid=pods_by_uid,
        )

    def _record_results(self, pods_by_uid, failed, errors) -> None:
        if self.recorder is None:
            return
        from ..events import events as ev

        for pod in failed:
            self.recorder.publish(ev.pod_failed_to_schedule(pod, errors.get(pod.uid)))
        for existing in self.existing_nodes:
            if existing.pods and self.cluster is not None:
                self.cluster.nominate_node_for_pod(existing.provider_id())
            for pod in existing.pods:
                self.recorder.publish(ev.nominate_pod(pod, existing.name()))

    # -- add one pod (scheduler.go:238) ------------------------------------

    def _add(self, pod: Pod) -> Optional[str]:
        # topology outcomes per claim depend only on per-domain counts and
        # the claim's concrete value set per key, so compute the admissible
        # domains once and skip claims that would be rejected anyway
        # (loops 1-2 discard the per-claim error strings, so skipping is
        # behavior-identical)
        strict_reqs = (
            strict_pod_requirements(pod)
            if has_preferred_node_affinity(pod)
            else pod_requirements(pod)
        )
        adm = self.topology.admissible_by_key(pod, strict_reqs)

        def claim_viable(reqs) -> bool:
            if adm is None:
                return True
            for key, allowed in adm.items():
                r = reqs.get_req(key)
                if r.complement:
                    continue  # NotIn/Exists/Gt/Lt: no concrete value set
                if allowed.isdisjoint(r.values):
                    return False
            return True

        # 1. in-flight real nodes
        for node in self.existing_nodes:
            if not claim_viable(node.requirements):
                continue
            if node.add(self.kube_client, pod) is None:
                return None

        # 2. already-planned claims, fewest pods first (scheduler.go:247)
        self.new_node_claims.sort(key=lambda c: len(c.pods))
        pod_requests = resources.requests_for_pods(pod)
        for claim in self.new_node_claims:
            if not claim_viable(claim.requirements):
                continue
            if claim.add(pod, pod_requests=pod_requests) is None:
                return None

        # 3. a new claim per template, in weight order
        errs = []
        for template in self.node_claim_templates:
            instance_types = self.instance_types.get(template.nodepool_name, [])
            remaining = self.remaining_resources.get(template.nodepool_name)
            if remaining is not None:
                instance_types = _filter_by_remaining_resources(instance_types, remaining)
                if not instance_types:
                    errs.append(
                        f'all available instance types exceed limits for nodepool: "{template.nodepool_name}"'
                    )
                    continue
            claim = SchedulingNodeClaim(
                template, self.topology, self.daemon_overhead[template.nodepool_name], instance_types
            )
            err = claim.add(pod, pod_requests=pod_requests)
            if err is not None:
                errs.append(
                    f'incompatible with nodepool "{template.nodepool_name}", '
                    f"daemonset overhead={resources.to_string(self.daemon_overhead[template.nodepool_name])}, {err}"
                )
                continue
            self.new_node_claims.append(claim)
            if template.nodepool_name in self.remaining_resources:
                # pessimistic: assume the largest surviving instance type
                # launches (scheduler.go:343 subtractMax)
                self.remaining_resources[template.nodepool_name] = _subtract_max(
                    self.remaining_resources[template.nodepool_name], claim.instance_type_options
                )
            return None
        return "; ".join(errs) if errs else "no nodepool matched"

    # -- existing nodes (scheduler.go:287) ---------------------------------

    def _calculate_existing_node_claims(
        self, state_nodes: List[StateNode], daemonset_pods: List[Pod]
    ) -> None:
        for node in state_nodes:
            daemons = []
            for p in daemonset_pods:
                if Taints(node.taints()).tolerates(p) is not None:
                    continue
                if label_requirements(node.labels()).compatible(pod_requirements(p), hint=False) is not None:
                    continue
                daemons.append(p)
            self.existing_nodes.append(
                ExistingNode(node, self.topology, resources.requests_for_pods(*daemons))
            )
            pool = node.labels().get(wk.NODEPOOL_LABEL_KEY, "")
            if pool in self.remaining_resources:
                self.remaining_resources[pool] = resources.subtract(
                    self.remaining_resources[pool], node.capacity()
                )
        # initialized nodes first so consolidation packs onto ready capacity
        # (scheduler.go:310-321)
        self.existing_nodes.sort(key=lambda n: (not n.initialized(), n.name()))


def _daemon_overhead(
    templates: List[NodeClaimTemplate], daemonset_pods: List[Pod]
) -> Dict[str, ResourceList]:
    """Per-template daemonset resource overhead (scheduler.go:324)."""
    overhead = {}
    for template in templates:
        daemons = []
        for p in daemonset_pods:
            if Taints(template.spec.taints).tolerates(p) is not None:
                continue
            if template.requirements.compatible(
                pod_requirements(p), ALLOW_UNDEFINED_WELL_KNOWN_LABELS, hint=False
            ) is not None:
                continue
            daemons.append(p)
        overhead[template.nodepool_name] = resources.requests_for_pods(*daemons)
    return overhead


def _subtract_max(remaining: ResourceList, instance_types: List[InstanceType]) -> ResourceList:
    """Pessimistic limit tracking: subtract the element-wise max capacity
    over possible instance types (scheduler.go:347 subtractMax)."""
    if not instance_types:
        return remaining
    it_max = resources.max_resources(*(it.capacity for it in instance_types))
    return {k: v - it_max.get(k, 0) for k, v in remaining.items()}


def _filter_by_remaining_resources(
    instance_types: List[InstanceType], remaining: ResourceList
) -> List[InstanceType]:
    """Drop instance types whose launch would breach NodePool limits
    (scheduler.go:367 filterByRemainingResources)."""
    out = []
    for it in instance_types:
        viable = True
        for name, rem in remaining.items():
            if it.capacity.get(name, 0) > rem:
                viable = False
        if viable:
            out.append(it)
    return out
