"""Preference relaxation ladder (ref
pkg/controllers/provisioning/scheduling/preferences.go).

When a pod can't schedule, soft constraints are peeled off one per
round, in a fixed order, and the pod is re-queued.
"""

from __future__ import annotations

from typing import Optional

from ..kube.objects import (
    EFFECT_PREFER_NO_SCHEDULE,
    Pod,
    SCHEDULE_ANYWAY,
    Toleration,
)


class Preferences:
    def __init__(self, tolerate_prefer_no_schedule: bool = False):
        # only added when some NodePool actually has a PreferNoSchedule taint
        # (scheduler.go:54-63)
        self.tolerate_prefer_no_schedule = tolerate_prefer_no_schedule

    def relax(self, pod: Pod) -> bool:
        """Try each relaxation; True if one applied (preferences.go:38)."""
        relaxations = [
            self._remove_required_node_affinity_term,
            self._remove_preferred_pod_affinity_term,
            self._remove_preferred_pod_anti_affinity_term,
            self._remove_preferred_node_affinity_term,
            self._remove_topology_spread_schedule_anyway,
        ]
        if self.tolerate_prefer_no_schedule:
            relaxations.append(self._tolerate_prefer_no_schedule_taints)
        for fn in relaxations:
            if fn(pod) is not None:
                # in-place spec mutation without a resource_version bump:
                # drop the pod's scheduling memo (solver.podcache) so the
                # next solve re-derives its signature from the relaxed spec
                pod.__dict__.pop("_karp_memo", None)
                return True
        return False

    @staticmethod
    def _remove_preferred_node_affinity_term(pod: Pod) -> Optional[str]:
        a = pod.spec.affinity
        if a is None or a.node_affinity is None or not a.node_affinity.preferred:
            return None
        terms = sorted(a.node_affinity.preferred, key=lambda t: -t.weight)
        removed = terms[0]
        a.node_affinity.preferred = terms[1:]
        return f"removing preferred node affinity term weight={removed.weight}"

    @staticmethod
    def _remove_required_node_affinity_term(pod: Pod) -> Optional[str]:
        a = pod.spec.affinity
        if (
            a is None
            or a.node_affinity is None
            or a.node_affinity.required is None
            or not a.node_affinity.required.node_selector_terms
        ):
            return None
        terms = a.node_affinity.required.node_selector_terms
        # OR semantics: drop the first term only if others remain
        # (preferences.go:84)
        if len(terms) > 1:
            a.node_affinity.required.node_selector_terms = terms[1:]
            return "removing required node affinity term[0]"
        return None

    @staticmethod
    def _remove_topology_spread_schedule_anyway(pod: Pod) -> Optional[str]:
        for i, tsc in enumerate(pod.spec.topology_spread_constraints):
            if tsc.when_unsatisfiable == SCHEDULE_ANYWAY:
                # swap-remove, like the reference (preferences.go:95)
                last = len(pod.spec.topology_spread_constraints) - 1
                pod.spec.topology_spread_constraints[i] = pod.spec.topology_spread_constraints[last]
                pod.spec.topology_spread_constraints.pop()
                return f"removing ScheduleAnyway topology spread on {tsc.topology_key}"
        return None

    @staticmethod
    def _remove_preferred_pod_affinity_term(pod: Pod) -> Optional[str]:
        a = pod.spec.affinity
        if a is None or a.pod_affinity is None or not a.pod_affinity.preferred:
            return None
        terms = sorted(a.pod_affinity.preferred, key=lambda t: -t.weight)
        a.pod_affinity.preferred = terms[1:]
        return "removing preferred pod affinity term[0]"

    @staticmethod
    def _remove_preferred_pod_anti_affinity_term(pod: Pod) -> Optional[str]:
        a = pod.spec.affinity
        if a is None or a.pod_anti_affinity is None or not a.pod_anti_affinity.preferred:
            return None
        terms = sorted(a.pod_anti_affinity.preferred, key=lambda t: -t.weight)
        a.pod_anti_affinity.preferred = terms[1:]
        return "removing preferred pod anti-affinity term[0]"

    @staticmethod
    def _tolerate_prefer_no_schedule_taints(pod: Pod) -> Optional[str]:
        toleration = Toleration(operator="Exists", effect=EFFECT_PREFER_NO_SCHEDULE)
        for t in pod.spec.tolerations:
            if t.match_toleration(toleration):
                return None
        pod.spec.tolerations.append(toleration)
        return "adding toleration for PreferNoSchedule taints"
