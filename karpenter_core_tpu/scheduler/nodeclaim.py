"""In-flight scheduling NodeClaim and NodeClaimTemplate (ref
pkg/controllers/provisioning/scheduling/nodeclaim.go,
nodeclaimtemplate.go)."""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..apis import labels as wk
from ..apis.nodeclaim import NodeClaim, NodeClaimResources, NodeClaimSpec
from ..apis.nodepool import NodePool
from ..cloudprovider.types import InstanceType, order_by_price
from ..kube.objects import OP_IN, ObjectMeta, OwnerReference, Pod, ResourceList, next_name
from ..scheduling import HostPortUsage, Requirement, Requirements, Taints, resources
from ..scheduling.hostports import get_host_ports
from ..scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    has_preferred_node_affinity,
    label_requirements,
    node_selector_requirements,
    pod_requirements,
    strict_pod_requirements,
)
from .topology import Topology

_hostname_counter = itertools.count(1)

MAX_INSTANCE_TYPES = 100  # nodeclaimtemplate.go:55 ToNodeClaim slice cap


class NodeClaimTemplate:
    """Per-NodePool template with pre-built requirements
    (nodeclaimtemplate.go:33)."""

    def __init__(self, nodepool: NodePool):
        self.nodepool_name = nodepool.name
        self.spec = nodepool.spec.template
        self.labels = dict(self.spec.metadata.labels)
        self.labels[wk.NODEPOOL_LABEL_KEY] = nodepool.name
        self.annotations = dict(self.spec.metadata.annotations)
        self.instance_type_options: List[InstanceType] = []
        self.requirements = Requirements()
        self.requirements.add(*node_selector_requirements(self.spec.requirements).values_list())
        self.requirements.add(*label_requirements(self.labels).values_list())
        self.taints = Taints(self.spec.taints)

    def to_node_claim(self, nodepool: NodePool, requirements: Requirements,
                      instance_types: List[InstanceType], requests: ResourceList) -> NodeClaim:
        """Stamp a NodeClaim CR (nodeclaimtemplate.go:55 ToNodeClaim):
        instance types capped at the 100 cheapest."""
        selected = order_by_price(instance_types, requirements)[:MAX_INSTANCE_TYPES]
        reqs = Requirements(*requirements.values_list())
        reqs.add(Requirement(wk.LABEL_INSTANCE_TYPE, OP_IN, [it.name for it in selected]))
        nc = NodeClaim()
        nc.metadata.name = next_name(self.nodepool_name)
        nc.metadata.labels = dict(self.labels)
        nc.metadata.annotations = {
            **self.annotations,
            wk.NODEPOOL_HASH_ANNOTATION_KEY: nodepool.static_hash(),
        }
        nc.metadata.owner_references = [
            OwnerReference(
                api_version="karpenter.sh/v1beta1",
                kind="NodePool",
                name=nodepool.name,
                uid=nodepool.uid,
                block_owner_deletion=True,
            )
        ]
        nc.spec = NodeClaimSpec(
            taints=list(self.spec.taints),
            startup_taints=list(self.spec.startup_taints),
            requirements=[r.to_node_selector_requirement() for r in reqs.values()],
            resources=NodeClaimResources(requests=dict(requests)),
            kubelet=self.spec.kubelet,
            node_class_ref=self.spec.node_class_ref,
        )
        return nc


def _max_allocatable(instance_types: List[InstanceType]) -> ResourceList:
    """Elementwise max allocatable over the surviving options — the
    add() fast screen's upper bound."""
    out: ResourceList = {}
    for it in instance_types:
        for name, value in it.allocatable().items():
            if value > out.get(name, 0):
                out[name] = value
    return out


class SchedulingNodeClaim:
    """A node we're planning to create: constraints + compatible pods +
    surviving instance types (nodeclaim.go:35)."""

    def __init__(
        self,
        template: NodeClaimTemplate,
        topology: Topology,
        daemon_resources: ResourceList,
        instance_types: List[InstanceType],
    ):
        hostname = f"hostname-placeholder-{next(_hostname_counter):04d}"
        topology.register(wk.LABEL_HOSTNAME, hostname)
        self.template = template
        self.nodepool_name = template.nodepool_name
        self.requirements = Requirements(*template.requirements.values_list())
        self.requirements.add(Requirement(wk.LABEL_HOSTNAME, OP_IN, [hostname]))
        self.instance_type_options = list(instance_types)
        self._max_alloc = _max_allocatable(self.instance_type_options)
        self.requests: ResourceList = dict(daemon_resources)
        self.daemon_resources = daemon_resources
        self.topology = topology
        self.host_port_usage = HostPortUsage()
        self.pods: List[Pod] = []

    def add(self, pod: Pod, pod_requests: Optional[ResourceList] = None) -> Optional[str]:
        """Try to place the pod; returns error string on failure without
        mutating state (nodeclaim.go:65 Add). ``pod_requests`` lets the
        scheduler's claim loop compute the pod's requests once across
        the many claims it probes."""
        if pod_requests is None:
            pod_requests = resources.requests_for_pods(pod)
        # fast resource screen: if some resource overflows the MAXIMUM
        # remaining allocatable across all surviving options, no option
        # fits — skip the per-attempt requirement algebra entirely (the
        # dominant cost when a pod probes hundreds of full claims)
        max_alloc = self._max_alloc
        requests = self.requests
        for name, value in pod_requests.items():
            if requests.get(name, 0) + value > max_alloc.get(name, 0):
                return "no instance type has sufficient remaining capacity"
        # taints
        err = Taints(self.template.spec.taints).tolerates(pod)
        if err:
            return err
        # host ports
        host_ports = get_host_ports(pod)
        err = self.host_port_usage.conflicts(pod, host_ports)
        if err:
            return f"checking host port usage, {err}"

        claim_requirements = Requirements(*self.requirements.values_list())
        pod_reqs = pod_requirements(pod)

        # nodeclaim affinity requirements
        err = claim_requirements.compatible(pod_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
        if err:
            return f"incompatible requirements, {err}"
        claim_requirements.add(*pod_reqs.values_list())

        strict_reqs = pod_reqs
        if has_preferred_node_affinity(pod):
            # preferences must not shrink the pod's domain choices
            # (nodeclaim.go:86-91)
            strict_reqs = strict_pod_requirements(pod)

        # topology
        from .topology import TopologyError

        try:
            topology_requirements = self.topology.add_requirements(
                strict_reqs, claim_requirements, pod, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            )
        except TopologyError as e:
            return str(e)
        err = claim_requirements.compatible(topology_requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
        if err:
            return err
        claim_requirements.add(*topology_requirements.values_list())

        # instance types
        requests = resources.merge(self.requests, pod_requests)
        filtered = filter_instance_types_by_requirements(
            self.instance_type_options, claim_requirements, requests
        )
        if not filtered.remaining:
            cumulative = resources.merge(self.daemon_resources, pod_requests)
            return (
                f"no instance type satisfied resources {resources.to_string(cumulative)} "
                f"and requirements {claim_requirements!r} ({filtered.failure_reason()})"
            )

        # commit
        self.pods.append(pod)
        self.instance_type_options = filtered.remaining
        self._max_alloc = _max_allocatable(filtered.remaining)
        self.requests = requests
        self.requirements = claim_requirements
        self.topology.record(pod, claim_requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
        self.host_port_usage.add(pod, host_ports)
        return None

    def finalize_scheduling(self) -> None:
        """Strip the placeholder hostname before launch (nodeclaim.go:123)."""
        self.requirements.pop(wk.LABEL_HOSTNAME, None)

    def to_node_claim(self, nodepool: NodePool) -> NodeClaim:
        return self.template.to_node_claim(
            nodepool, self.requirements, self.instance_type_options, self.requests
        )


@dataclass
class FilterResults:
    """Instance-type filter outcome with per-criterion tracking for rich
    failure messages (nodeclaim.go:144)."""

    remaining: List[InstanceType] = field(default_factory=list)
    requirements_met: bool = False
    fits: bool = False
    has_offering: bool = False
    requirements_and_fits: bool = False
    requirements_and_offering: bool = False
    fits_and_offering: bool = False
    requests: ResourceList = field(default_factory=dict)

    def failure_reason(self) -> str:
        if self.remaining:
            return ""
        r = self
        if not r.requirements_met and not r.fits and not r.has_offering:
            return "no instance type met the scheduling requirements or had enough resources or had a required offering"
        if not r.requirements_met and not r.fits:
            return "no instance type met the scheduling requirements or had enough resources"
        if not r.requirements_met and not r.has_offering:
            return "no instance type met the scheduling requirements or had a required offering"
        if not r.fits and not r.has_offering:
            return "no instance type had enough resources or had a required offering"
        if not r.requirements_met:
            return "no instance type met all requirements"
        if not r.fits:
            msg = "no instance type has enough resources"
            if r.requests.get("cpu", 0) >= 10**6 * 10**9:
                msg += " (CPU request >= 1 Million, m vs M typo?)"
            return msg
        if not r.has_offering:
            return "no instance type has the required offering"
        if r.requirements_and_fits:
            return "no instance type which met the scheduling requirements and had enough resources, had a required offering"
        if r.fits_and_offering:
            return "no instance type which had enough resources and the required offering met the scheduling requirements"
        if r.requirements_and_offering:
            return "no instance type which met the scheduling requirements and the required offering had the required resources"
        return "no instance type met the requirements/resources/offering tuple"


def _compatible(it: InstanceType, requirements: Requirements) -> bool:
    return it.requirements.intersects(requirements) is None


def _fits(it: InstanceType, requests: ResourceList) -> bool:
    return resources.fits(requests, it.allocatable())


def _has_offering(it: InstanceType, requirements: Requirements) -> bool:
    for o in it.offerings.available():
        if (
            not requirements.has(wk.LABEL_TOPOLOGY_ZONE)
            or requirements.get_req(wk.LABEL_TOPOLOGY_ZONE).has(o.zone)
        ) and (
            not requirements.has(wk.CAPACITY_TYPE_LABEL_KEY)
            or requirements.get_req(wk.CAPACITY_TYPE_LABEL_KEY).has(o.capacity_type)
        ):
            return True
    return False


def filter_instance_types_by_requirements(
    instance_types: List[InstanceType], requirements: Requirements, requests: ResourceList
) -> FilterResults:
    """No short-circuit: each criterion is tracked independently so the
    error message can name what excluded everything (nodeclaim.go:225).

    The hot path evaluates the three criteria as vectors against the
    tensor path's cached catalog encodings (solver.oracle_bridge) —
    per-pod-per-claim Python set algebra dominated the diverse-mix
    profile; the exact per-type loop remains as the fallback for
    shapes the bridge doesn't vectorize (Gt/Lt bounds, unregistered
    type lists)."""
    from ..solver.oracle_bridge import fast_filter, register_filtered

    results = FilterResults(requests=requests)
    vec = fast_filter(instance_types, requirements, requests)
    if vec is not None:
        compat, fits, offering = vec
        results.requirements_met = bool(compat.any())
        results.fits = bool(fits.any())
        results.has_offering = bool(offering.any())
        results.requirements_and_fits = bool((compat & fits & ~offering).any())
        results.requirements_and_offering = bool((compat & offering & ~fits).any())
        results.fits_and_offering = bool((fits & offering & ~compat).any())
        keep = compat & fits & offering
        results.remaining = [instance_types[j] for j in np.flatnonzero(keep)]
        register_filtered(instance_types, keep, results.remaining)
        return results
    for it in instance_types:
        it_compat = _compatible(it, requirements)
        it_fits = _fits(it, requests)
        it_offering = _has_offering(it, requirements)
        results.requirements_met |= it_compat
        results.fits |= it_fits
        results.has_offering |= it_offering
        results.requirements_and_fits |= it_compat and it_fits and not it_offering
        results.requirements_and_offering |= it_compat and it_offering and not it_fits
        results.fits_and_offering |= it_fits and it_offering and not it_compat
        if it_compat and it_fits and it_offering:
            results.remaining.append(it)
    return results
