"""Provisioner: batch → sync gate → schedule → create NodeClaims (ref
pkg/controllers/provisioning/provisioner.go).

``use_tpu_solver`` switches Schedule's backend between the greedy oracle
and the batched TPU solver; in TPU mode the plans are converted into the
same NodeClaim CRs the oracle path stamps, keeping everything downstream
(lifecycle, disruption) backend-agnostic.
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..apis import labels as wk
from ..apis.nodeclaim import NodeClaim
from ..kube.objects import Pod
from ..scheduling import resources
from ..scheduler.builder import NodePoolsNotFoundError, build_scheduler
from ..scheduler.nodeclaim import SchedulingNodeClaim
from ..scheduler.scheduler import Results, SchedulerOptions
from ..scheduler.volumetopology import VolumeTopology
from ..state.cluster import Cluster
from ..tracing import tracer
from ..utils import pod as podutils
from ..utils.pretty import ChangeMonitor
from .batcher import Batcher


@dataclass
class LaunchOptions:
    """provisioner.go:40-73."""

    record_pod_nomination: bool = False
    reason: str = "provisioning"


class LimitsExceededError(Exception):
    pass


class Provisioner:
    def __init__(
        self,
        kube_client,
        cloud_provider,
        cluster: Cluster,
        recorder=None,
        batcher: Optional[Batcher] = None,
        use_tpu_solver: bool = False,
        metrics=None,
    ):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.cluster = cluster
        self.recorder = recorder
        self.batcher = batcher or Batcher()
        self.use_tpu_solver = use_tpu_solver
        self.metrics = metrics
        self._change_monitor = ChangeMonitor()
        self._parity_solve_count = 0
        self._parity_inflight = False
        # steady-state ticks reuse one TPUScheduler while the nodepool
        # set is unchanged (identity + resource_version): the solver's
        # cross-tick caches are provider-keyed module state either way,
        # but reuse keeps pool ordering/filtering off the tick path and
        # `last_timings`/`last_cache_stats` continuous for debugging
        self._tpu_solver = None  # (nodepool key, TPUScheduler)
        # serving double-buffer hook, forwarded to the live TPUScheduler:
        # fires when the authoritative encode hands off to device pack
        # (serving/pipeline.py overlaps the next batch's prewarm with it)
        self.encode_done_listener = None

    def trigger(self) -> None:
        self.batcher.trigger()

    # -- reconcile (provisioner.go:114) ------------------------------------

    def reconcile(self, wait_for_batch: bool = False) -> Tuple[List[str], Optional[str]]:
        """One pass: returns (created nodeclaim names, requeue reason).
        The pass runs under one trace root (batch → schedule → solve →
        claim creation); the trace is buffered only when a solve ran, so
        idle reconciles can't evict real solve traces."""
        names, reason, _results = self.reconcile_with_results(wait_for_batch)
        return names, reason

    def reconcile_with_results(
        self, wait_for_batch: bool = False
    ) -> Tuple[List[str], Optional[str], Optional[Results]]:
        """``reconcile`` with the scheduling Results exposed — the
        serving pipeline (serving/pipeline.py) reads per-plan pod
        membership off them for decision-latency accounting and the
        traffic simulator's kubelet binder. Each successfully created
        claim's name is stamped on its plan/claim object as
        ``created_claim_name``."""
        import time as _time

        batch_t0 = _time.perf_counter()
        if wait_for_batch:
            if not self.batcher.wait():
                return [], None, None
        batch_wait_ms = round((_time.perf_counter() - batch_t0) * 1000.0, 3)
        if not self.cluster.synced():
            return [], "waiting on cluster sync", None
        with tracer.trace_root(
            "provisioner.reconcile", buffer_if="solve", batch_wait_ms=batch_wait_ms
        ):
            results = self.schedule()
            if results is None:
                return [], None, None
            names: List[str] = []
            create_errors: List[str] = []
            opts = LaunchOptions(record_pod_nomination=True, reason="provisioning")
            with tracer.span("create_node_claims"):
                if results.new_node_claims:
                    created, errs = self.create_node_claims(results.new_node_claims, opts)
                    names.extend(created)
                    create_errors.extend(errs)
                for plan in getattr(results, "tpu_plans", []):
                    try:
                        name = self.create_from_plan(plan, opts)
                        plan.created_claim_name = name
                        names.append(name)
                    except Exception as e:  # noqa: BLE001 — one failed plan must not skip the rest
                        create_errors.append(f"creating node claim from plan, {e}")
        # surface failures instead of looking like "nothing to do"
        reason = "; ".join(create_errors[:5]) if create_errors else None
        return names, reason, results

    # -- pod discovery (provisioner.go:155-178) ----------------------------

    def get_pending_pods(self) -> List[Pod]:
        pods = []
        vt = VolumeTopology(self.kube_client)
        for pod in self.kube_client.list("Pod", filter_fn=lambda p: not p.spec.node_name):
            if not podutils.is_provisionable(pod):
                continue
            err = vt.validate_persistent_volume_claims(pod)
            if err is not None:
                continue
            pods.append(pod)
        return pods

    # -- schedule (provisioner.go:298) -------------------------------------

    def schedule(self) -> Optional[Results]:
        # scheduling_duration is observed by the operator's reconcile
        # wrapper (operator.py) — observing here too would double-count
        # snapshot nodes BEFORE listing pods to avoid over-provisioning
        # (provisioner.go:301-312)
        with tracer.span("snapshot_nodes"):
            nodes = self.cluster.deep_copy_nodes()
        active = [n for n in nodes if not n.marked_for_deletion]
        deleting = [n for n in nodes if n.marked_for_deletion]
        with tracer.span("pending_pods"):
            pending = self.get_pending_pods()
        # pods on deleting nodes need replacement capacity
        # (provisioner.go:317-323)
        deleting_pods: List[Pod] = []
        for n in deleting:
            for ns, name in n.pod_requests:
                pod = self.kube_client.get("Pod", name, namespace=ns)
                if pod is not None and podutils.is_reschedulable(pod):
                    deleting_pods.append(pod)
        pods = pending + deleting_pods
        if not pods:
            return Results()

        nodepools = [
            np_
            for np_ in self.kube_client.list("NodePool")
            if np_.metadata.deletion_timestamp is None
        ]
        if not nodepools:
            # once-per-hour dedup'd warning (provisioner.go:182-199 via
            # pretty.ChangeMonitor)
            if self._change_monitor.has_changed("no-nodepools", True):
                logging.getLogger("karpenter").warning(
                    "no nodepools found; provisioning is disabled until one is created"
                )
            return Results()
        # the TPU path handles existing capacity itself (packs onto free
        # space before opening nodes) and falls back to the oracle only
        # for the constraint classes it can't tensorize
        if self.use_tpu_solver:
            return self._schedule_tpu(pods, nodepools, active)
        try:
            scheduler = build_scheduler(
                self.kube_client,
                self.cluster,
                nodepools,
                self.cloud_provider,
                pods,
                state_nodes=active,
                daemonset_pods=self.cluster.get_daemonset_pods(),
                recorder=self.recorder,
                opts=SchedulerOptions(),
            )
        except NodePoolsNotFoundError:
            return Results()
        with tracer.trace_root("oracle_solve", is_solve=True, pods=len(pods)):
            return scheduler.solve(pods)

    def _schedule_tpu(self, pods: List[Pod], nodepools, state_nodes=None) -> Results:
        """TPU path: solve plans, then re-express them as scheduler results
        via single-claim templates so CreateNodeClaims is uniform."""
        from ..solver import TPUScheduler

        key = tuple(
            (id(np_), np_.metadata.resource_version) for np_ in nodepools
        )
        cached = self._tpu_solver
        if cached is not None and cached[0] == key:
            solver = cached[1]
        else:
            solver = TPUScheduler(
                nodepools,
                self.cloud_provider,
                kube_client=self.kube_client,
                cluster=self.cluster,
                recorder=self.recorder,
                metrics=self.metrics,
            )
            # the held nodepool list keeps the key's id()s stable
            self._tpu_solver = (key, solver, list(nodepools))
        solver.encode_done_listener = self.encode_done_listener
        sr = solver.solve(
            pods,
            state_nodes=state_nodes,
            daemonset_pods=self.cluster.get_daemonset_pods(),
        )
        results = sr.oracle_results or Results()
        results.pod_errors.update(sr.pod_errors)
        by_uid = {p.uid: p for p in pods}
        # the oracle fallback publishes its own failure events inside
        # solve(); mirror only the tensor-path errors here so the event
        # stream is backend-agnostic without duplicates
        if self.recorder is not None and sr.pod_errors:
            from ..events import events as ev

            oracle_errs = (
                sr.oracle_results.pod_errors if sr.oracle_results is not None else {}
            )
            for uid, err in sr.pod_errors.items():
                pod = by_uid.get(uid)
                if pod is not None and uid not in oracle_errs:
                    self.recorder.publish(ev.pod_failed_to_schedule(pod, err))
        results._pods_by_uid.update(by_uid)
        if sr.node_plans:
            for plan in sr.node_plans:
                plan.pods = [pods[i] for i in plan.pod_indices]
            results.tpu_plans = sr.node_plans  # consumed by reconcile
        # tensor-path placements onto existing nodes are nominations —
        # mirror the oracle's _record_results (nominate + event, no claim)
        for plan in sr.existing_plans:
            plan.pods = [pods[i] for i in plan.pod_indices]
            if self.cluster is not None:
                self.cluster.nominate_node_for_pod(plan.state_node.provider_id())
            if self.recorder is not None:
                from ..events import events as ev

                for pod in plan.pods:
                    self.recorder.publish(ev.nominate_pod(pod, plan.state_node.name()))
        self._maybe_observe_parity(pods, nodepools)
        return results

    # every Nth tensor solve shadows a pod subsample through the oracle
    # and records node-count parity — the live analogue of the bench's
    # parity gate; 0 disables
    try:
        PARITY_SAMPLE_EVERY = max(0, int(os.environ.get("KARPENTER_TPU_PARITY_SAMPLE", "16")))
    except ValueError:
        PARITY_SAMPLE_EVERY = 16
    PARITY_SUBSAMPLE = 500

    def _maybe_observe_parity(self, pods: List[Pod], nodepools) -> None:
        if self.metrics is None or self.PARITY_SAMPLE_EVERY <= 0 or len(pods) < 8:
            return
        self._parity_solve_count += 1
        if self._parity_solve_count % self.PARITY_SAMPLE_EVERY:
            return
        # the shadow only sets a gauge — run it off the provisioning
        # path so the O(P·N) oracle solve never delays NodeClaim
        # creation. Single-flight: a slow oracle must not pile threads
        # up behind the GIL.
        if getattr(self, "_parity_inflight", False):
            return
        self._parity_inflight = True
        import copy as _copy

        # deep-copy the subsample: the oracle's preference relaxation
        # mutates pods in place (scheduler.py relax), and these are the
        # provisioner's LIVE objects, read concurrently by the main loop
        sub = _copy.deepcopy(pods[: self.PARITY_SUBSAMPLE])
        threading.Thread(
            target=self._observe_parity, args=(sub, list(nodepools)), daemon=True
        ).start()

    def _observe_parity(self, sub: List[Pod], nodepools) -> None:
        try:
            from ..scheduler.builder import build_scheduler
            from ..solver import TPUScheduler

            # the shadow's traces must not displace live solve traces in
            # /debug/traces (it runs the same instrumented pipeline)
            with tracer.trace_root("parity_shadow", buffer_if="never"):
                o = build_scheduler(
                    self.kube_client, None, nodepools, self.cloud_provider, sub
                ).solve(sub)
                t = TPUScheduler(
                    nodepools, self.cloud_provider, kube_client=self.kube_client
                ).solve(sub)
            o_scheduled = sum(len(c.pods) for c in o.new_node_claims)
            o_nodes = len(o.new_node_claims)
            if t.pods_scheduled < o_scheduled:
                # scheduling fewer pods must read as a parity failure,
                # not as "fewer nodes = perfect"
                parity = 0.0
            elif t.node_count <= o_nodes:
                # one-sided: as few or fewer nodes than the oracle (incl.
                # both opening none) is full parity
                parity = 1.0
            else:
                parity = o_nodes / t.node_count
            self.metrics.solver_parity.set(parity)
        except Exception:
            # the shadow must never break provisioning, but a broken
            # shadow should not fail silently forever either
            logging.getLogger("karpenter").debug(
                "parity shadow solve failed", exc_info=True
            )
        finally:
            self._parity_inflight = False

    # -- create (provisioner.go:141-153, 341-367) --------------------------

    def create_node_claims(
        self, claims: List[SchedulingNodeClaim], options: Optional[LaunchOptions] = None
    ) -> Tuple[List[str], List[str]]:
        options = options or LaunchOptions()
        names: List[str] = []
        errors: List[str] = []
        with concurrent.futures.ThreadPoolExecutor(max_workers=max(1, min(len(claims), 16))) as ex:
            futures = {ex.submit(self.create, c, options): c for c in claims}
            for fut in concurrent.futures.as_completed(futures):
                try:
                    names.append(fut.result())
                except Exception as e:  # noqa: BLE001 — collected like multierr
                    errors.append(f"creating node claim, {e}")
        return names, errors

    def create(self, claim: SchedulingNodeClaim, options: Optional[LaunchOptions] = None) -> str:
        options = options or LaunchOptions()
        latest = self.kube_client.get("NodePool", claim.nodepool_name)
        if latest is None:
            raise LimitsExceededError(f"nodepool {claim.nodepool_name} not found")
        err = self._limits_exceeded(latest)
        if err:
            raise LimitsExceededError(err)
        node_claim = claim.to_node_claim(latest)
        self.kube_client.create(node_claim)
        # serving-layer correlation: which stored claim came from this
        # scheduling claim (oracle claims lose the association otherwise)
        claim.created_claim_name = node_claim.name
        if self.metrics is not None:
            self.metrics.nodeclaims_created.inc(
                reason=options.reason, nodepool=claim.nodepool_name
            )
        if options.record_pod_nomination and self.recorder is not None:
            from ..events import events as ev

            for pod in claim.pods:
                self.recorder.publish(ev.nominate_pod(pod, node_claim.name))
        return node_claim.name

    def create_from_plan(self, plan, options: Optional[LaunchOptions] = None) -> str:
        """Stamp a NodeClaim CR from a TPU solver NodePlan: instance type,
        zone and capacity type are already decided, so the claim pins them."""
        from ..apis.nodeclaim import NodeClaimResources, NodeClaimSpec
        from ..kube.objects import NodeSelectorRequirement, OwnerReference, next_name

        options = options or LaunchOptions()
        nodepool = self.kube_client.get("NodePool", plan.nodepool_name)
        if nodepool is None:
            raise LimitsExceededError(f"nodepool {plan.nodepool_name} not found")
        err = self._limits_exceeded(nodepool)
        if err:
            raise LimitsExceededError(err)
        template = nodepool.spec.template
        nc = NodeClaim()
        nc.metadata.name = next_name(plan.nodepool_name)
        nc.metadata.labels = {
            **template.metadata.labels,
            wk.NODEPOOL_LABEL_KEY: plan.nodepool_name,
        }
        nc.metadata.annotations = {
            **template.metadata.annotations,
            wk.NODEPOOL_HASH_ANNOTATION_KEY: nodepool.static_hash(),
        }
        nc.spec = NodeClaimSpec(
            taints=list(template.taints),
            startup_taints=list(template.startup_taints),
            requirements=(
                [
                    NodeSelectorRequirement(wk.LABEL_INSTANCE_TYPE, "In", [plan.instance_type.name]),
                    NodeSelectorRequirement(wk.LABEL_TOPOLOGY_ZONE, "In", [plan.zone]),
                    NodeSelectorRequirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", [plan.capacity_type]),
                ]
                # the solver's merged (template ∩ pods) requirements —
                # the launched node must carry every label the member
                # pods select on (nodeclaimtemplate.go:55 stamping)
                + [
                    r.to_node_selector_requirement()
                    for r in (plan.requirements.values() if plan.requirements else [])
                    if r.key
                    not in (
                        wk.LABEL_INSTANCE_TYPE,
                        wk.LABEL_TOPOLOGY_ZONE,
                        wk.CAPACITY_TYPE_LABEL_KEY,
                    )
                ]
            ),
            kubelet=template.kubelet,
            node_class_ref=template.node_class_ref,
        )
        nc.spec.resources = NodeClaimResources(requests=dict(plan.requests or {}))
        nc.metadata.owner_references = [
            OwnerReference(
                api_version="karpenter.sh/v1beta1",
                kind="NodePool",
                name=nodepool.name,
                uid=nodepool.uid,
                block_owner_deletion=True,
            )
        ]
        self.kube_client.create(nc)
        if self.metrics is not None:
            self.metrics.nodeclaims_created.inc(
                reason=options.reason, nodepool=plan.nodepool_name
            )
        if options.record_pod_nomination and self.recorder is not None:
            from ..events import events as ev

            for pod in getattr(plan, "pods", None) or []:
                self.recorder.publish(ev.nominate_pod(pod, nc.metadata.name))
        return nc.metadata.name

    @staticmethod
    def _limits_exceeded(nodepool) -> Optional[str]:
        """Limits.ExceededBy(status.resources) (nodepool.go:127 Limits)."""
        for name, limit in nodepool.spec.limits.items():
            usage = nodepool.status.resources.get(name, 0)
            if usage > limit:
                return f"limit exceeded for resource {name}"
        return None
