from .provisioner import Provisioner, LaunchOptions
from .batcher import Batcher
