"""Pod batching window (ref pkg/controllers/provisioning/batcher.go):
1 s idle / 10 s max (options.go:96-97).

Wakeups are condition-variable driven: ``trigger()`` notifies the
waiter directly, so the idle-path decision latency has no polling
floor (the previous implementation slept in 50 ms increments, which
put a hard 0-50 ms tax on every batch close — measurable once the
serving pipeline's solve times dropped under the poll interval).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class Batcher:
    def __init__(
        self,
        idle_seconds: float = 1.0,
        max_seconds: float = 10.0,
        # pure in-process window durations — monotonic, immune to skew
        clock: Callable[[], float] = time.monotonic,
    ):
        self.idle_seconds = idle_seconds
        self.max_seconds = max_seconds
        self.clock = clock
        self._cv = threading.Condition()
        self._pending = False

    def trigger(self) -> None:
        with self._cv:
            self._pending = True
            self._cv.notify_all()

    def wait(self, poll: Optional[float] = None, blocking: bool = True) -> bool:
        """Block until a batch has formed: first trigger starts the window,
        it closes after `idle` seconds without new triggers or `max`
        overall (batcher.go:52 Wait). Returns False if never triggered.

        ``poll`` is accepted for backward compatibility and ignored —
        the wait is event-driven; the only timed sleeps are the window
        deadlines themselves.
        """
        with self._cv:
            if not self._pending:
                if not blocking:
                    return False
                deadline = time.monotonic() + self.max_seconds
                while not self._pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._cv.wait(timeout=remaining)
            start = self.clock()
            last = start
            self._pending = False
            while True:
                now = self.clock()
                if now - last >= self.idle_seconds or now - start >= self.max_seconds:
                    return True
                # sleep exactly until the earlier of the two deadlines; a
                # trigger wakes us immediately and restarts the idle window
                remaining = min(
                    self.idle_seconds - (now - last), self.max_seconds - (now - start)
                )
                self._cv.wait(timeout=remaining)
                if self._pending:
                    self._pending = False
                    last = self.clock()
