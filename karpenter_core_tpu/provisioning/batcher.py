"""Pod batching window (ref pkg/controllers/provisioning/batcher.go):
1 s idle / 10 s max (options.go:96-97)."""

from __future__ import annotations

import threading
import time
from typing import Callable


class Batcher:
    def __init__(
        self,
        idle_seconds: float = 1.0,
        max_seconds: float = 10.0,
        clock: Callable[[], float] = time.time,
    ):
        self.idle_seconds = idle_seconds
        self.max_seconds = max_seconds
        self.clock = clock
        self._trigger = threading.Event()

    def trigger(self) -> None:
        self._trigger.set()

    def wait(self, poll: float = 0.05, blocking: bool = True) -> bool:
        """Block until a batch has formed: first trigger starts the window,
        it closes after `idle` seconds without new triggers or `max`
        overall (batcher.go:52 Wait). Returns False if never triggered."""
        if not self._trigger.wait(timeout=self.max_seconds if blocking else 0):
            return False
        start = self.clock()
        last = start
        self._trigger.clear()
        while True:
            if self._trigger.is_set():
                self._trigger.clear()
                last = self.clock()
            now = self.clock()
            if now - last >= self.idle_seconds or now - start >= self.max_seconds:
                return True
            time.sleep(poll)
