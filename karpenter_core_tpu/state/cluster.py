"""Cluster: the in-memory mirror of nodes/nodeclaims/pods/daemonsets
(ref pkg/controllers/state/cluster.go).

All durable state stays in the (in-memory) apiserver — this cache is
rebuilt from watches on restart and gated by ``synced()``, exactly the
reference's checkpoint-free design (SURVEY §5 checkpoint/resume). It is
also the source of the fleet snapshot the TPU consolidation repack
tensorizes.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import NodeClaim
from ..kube.client import KubeClient
from ..kube.objects import DaemonSet, Node, Pod
from ..scheduling import resources
from ..utils import pod as podutils
from .statenode import StateNode


class Cluster:
    # analysis: allow-clock(nomination/consolidation stamps are exchanged with kube-object wall-clock stamps)
    def __init__(self, kube_client: KubeClient, cloud_provider=None, clock: Callable[[], float] = time.time):
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.clock = clock
        self._mu = threading.RLock()
        # providerID → StateNode (cluster.go:48-68)
        self.nodes: Dict[str, StateNode] = {}
        self.bindings: Dict[tuple, str] = {}  # pod key → node name
        self.node_name_to_provider_id: Dict[str, str] = {}
        self.node_claim_name_to_provider_id: Dict[str, str] = {}
        self.daemonset_pods: Dict[tuple, Pod] = {}
        self.anti_affinity_pods: Dict[tuple, Pod] = {}
        # node name → {csi driver: attachable-volume limit}, applied to
        # the state node's volume usage (volumeusage.go CSINode hydration)
        self._csi_limits_by_node: Dict[str, Dict[str, int]] = {}
        self._unsynced_start: Optional[float] = None
        self._consolidation_timestamp: float = clock()
        # monotonic mutation counter: bumped on every state change the
        # solver's cross-tick caches could observe (nodes, claims, pod
        # bindings, daemonsets, CSI limits, deletion marks). The
        # incremental solve (solver/incremental.py) scopes its topology
        # seed cache to this value — unchanged generation proves the
        # cluster-derived inputs of a warm solve are unchanged.
        self._generation: int = 0

    def generation(self) -> int:
        with self._mu:
            return self._generation

    def _bump(self) -> None:
        # callers hold self._mu (RLock) — every mutator below does
        self._generation += 1

    # -- sync gate (cluster.go:89) -----------------------------------------

    def synced(self) -> bool:
        """True when the in-memory state covers at least everything the
        apiserver has (superset check)."""
        node_claims = self.kube_client.list("NodeClaim")
        nodes = self.kube_client.list("Node")
        with self._mu:
            state_claims = set(self.node_claim_name_to_provider_id)
            state_nodes = set(self.node_name_to_provider_id)
        for nc in node_claims:
            if not nc.status.provider_id:
                return False
            if nc.name not in state_claims:
                return False
        for n in nodes:
            if n.name not in state_nodes:
                return False
        return True

    # -- iteration ---------------------------------------------------------

    def for_each_node(self, fn: Callable[[StateNode], bool]) -> None:
        with self._mu:
            nodes = sorted(self.nodes.values(), key=lambda n: n.name())
        for n in nodes:
            if not fn(n):
                return

    def deep_copy_nodes(self) -> List[StateNode]:
        """Snapshot for scheduling (provisioner.go:310 deep copy)."""
        with self._mu:
            return [n.deep_copy() for n in self.nodes.values()]

    def for_pods_with_anti_affinity(self, fn: Callable[[Pod, Optional[Node]], bool]) -> None:
        """Each bound pod with required anti-affinity (cluster.go:128)."""
        with self._mu:
            items = list(self.anti_affinity_pods.items())
        for key, pod in items:
            with self._mu:
                node_name = self.bindings.get(key)
            if node_name is None:
                continue
            node = self.kube_client.get("Node", node_name)
            if node is None:
                continue
            if not fn(pod, node):
                return

    # -- nomination (cluster.go:172-194) -----------------------------------

    def is_node_nominated(self, provider_id: str) -> bool:
        with self._mu:
            n = self.nodes.get(provider_id)
            return n is not None and n.nominated(self.clock())

    def nominate_node_for_pod(self, provider_id: str) -> None:
        with self._mu:
            n = self.nodes.get(provider_id)
            if n is not None:
                n.nominate(self.clock())

    # -- deletion marks (cluster.go:195-219) -------------------------------

    def mark_for_deletion(self, *provider_ids: str) -> None:
        with self._mu:
            self._bump()
            for pid in provider_ids:
                n = self.nodes.get(pid)
                if n is not None:
                    n.marked_for_deletion = True

    def unmark_for_deletion(self, *provider_ids: str) -> None:
        with self._mu:
            self._bump()
            for pid in provider_ids:
                n = self.nodes.get(pid)
                if n is not None:
                    n.marked_for_deletion = False

    # -- nodeclaim / node updates (cluster.go:220-271) ---------------------

    def update_node_claim(self, node_claim: NodeClaim) -> None:
        with self._mu:
            self._bump()
            if node_claim.status.provider_id:
                old = self.nodes.get(node_claim.status.provider_id)
                state = StateNode(old.node if old else None, node_claim)
                self._carry_pods(old, state)
                self.nodes[node_claim.status.provider_id] = state
                self.node_claim_name_to_provider_id[node_claim.name] = node_claim.status.provider_id
                self._trigger_consolidation(old, state)
            else:
                # still tracked for Synced(); no state node until launch
                self.node_claim_name_to_provider_id.setdefault(node_claim.name, "")

    def delete_node_claim(self, name: str) -> None:
        with self._mu:
            self._bump()
            pid = self.node_claim_name_to_provider_id.pop(name, None)
            if pid:
                state = self.nodes.get(pid)
                if state is not None:
                    if state.node is None:
                        del self.nodes[pid]
                    else:
                        state.node_claim = None
            self.mark_unconsolidated()

    def update_node(self, node: Node) -> None:
        with self._mu:
            self._bump()
            pid = node.spec.provider_id or node.name
            old_pid = self.node_name_to_provider_id.get(node.name)
            old = self.nodes.get(pid) or (self.nodes.get(old_pid) if old_pid else None)
            if old_pid and old_pid != pid:
                # the node's provider id changed (e.g. stamped after
                # registration) — drop the stale entry or it double-counts
                self.nodes.pop(old_pid, None)
            state = StateNode(node, old.node_claim if old else None)
            self._carry_pods(old, state)
            # the CSINode cache is the single source of truth for attach
            # limits: it survives claim-only state (which never enters
            # node_name_to_provider_id, so update_csi_node can't reach
            # it) and clears stale limits after CSINode deletion. On a
            # cache miss (node re-created before its CSINode event
            # replays) fall back to the stored CSINode so a still-live
            # registration isn't treated as unlimited.
            limits = self._csi_limits_by_node.get(node.name)
            if limits is None:
                csi = self.kube_client.get("CSINode", node.name)
                if csi is not None:
                    limits = {
                        d.name: d.allocatable_count
                        for d in csi.drivers
                        if d.allocatable_count is not None
                    }
                    self._csi_limits_by_node[node.name] = limits
            state.volume_usage.csi_limits = dict(limits or {})
            self.nodes[pid] = state
            self.node_name_to_provider_id[node.name] = pid
            # re-link nodeclaim by provider id
            for nc_name, nc_pid in self.node_claim_name_to_provider_id.items():
                if nc_pid == pid and state.node_claim is None:
                    nc = self.kube_client.get("NodeClaim", nc_name)
                    if nc is not None:
                        state.node_claim = nc
            # replay pod bindings observed before this node arrived (watch
            # ordering can deliver bound pods first)
            if old is None:
                for (ns, name), bound_node in list(self.bindings.items()):
                    if bound_node == node.name and (ns, name) not in state.pod_requests:
                        pod = self.kube_client.get("Pod", name, namespace=ns)
                        if pod is not None:
                            state.update_for_pod(pod)
            self._trigger_consolidation(old, state)

    def delete_node(self, name: str) -> None:
        with self._mu:
            self._bump()
            # drop cached CSI attach limits so a re-created node with the
            # same name can't inherit stale limits before its CSINode event
            self._csi_limits_by_node.pop(name, None)
            pid = self.node_name_to_provider_id.pop(name, None)
            if pid:
                state = self.nodes.get(pid)
                if state is not None:
                    if state.node_claim is None:
                        del self.nodes[pid]
                    else:
                        state.node = None
            self.mark_unconsolidated()

    @staticmethod
    def _carry_pods(old: Optional[StateNode], new: StateNode) -> None:
        if old is None:
            return
        new.pod_requests = dict(old.pod_requests)
        new.pod_limits = dict(old.pod_limits)
        new.daemonset_requests = dict(old.daemonset_requests)
        new.daemonset_limits = dict(old.daemonset_limits)
        new.host_port_usage = old.host_port_usage
        new.volume_usage = old.volume_usage
        new.marked_for_deletion = old.marked_for_deletion
        new.nominated_until = old.nominated_until

    def _trigger_consolidation(self, old: Optional[StateNode], new: StateNode) -> None:
        """State transitions that may open consolidation opportunities
        (cluster.go:559)."""
        if old is None or old.initialized() != new.initialized() or old.marked_for_deletion != new.marked_for_deletion:
            self.mark_unconsolidated()

    # -- pod updates (cluster.go:273-297) ----------------------------------

    def update_pod(self, pod: Pod) -> None:
        with self._mu:
            self._bump()
            if podutils.is_terminal(pod):
                self._remove_pod_usage((pod.namespace, pod.name))
            else:
                self._cleanup_old_bindings(pod)
                if pod.spec.node_name:
                    # the binding is recorded even when the node isn't known
                    # yet; update_node replays it on arrival
                    self.bindings[(pod.namespace, pod.name)] = pod.spec.node_name
                    pid = self.node_name_to_provider_id.get(pod.spec.node_name, pod.spec.node_name)
                    state = self.nodes.get(pid)
                    if state is not None:
                        state.update_for_pod(pod)
            self._track_anti_affinity(pod)

    def _track_anti_affinity(self, pod: Pod) -> None:
        if podutils.has_required_pod_anti_affinity(pod):
            self.anti_affinity_pods[(pod.namespace, pod.name)] = pod
        else:
            self.anti_affinity_pods.pop((pod.namespace, pod.name), None)

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._mu:
            self._bump()
            self.anti_affinity_pods.pop((namespace, name), None)
            self._remove_pod_usage((namespace, name))
            self.mark_unconsolidated()

    def _remove_pod_usage(self, key: tuple) -> None:
        node_name = self.bindings.pop(key, None)
        if node_name:
            pid = self.node_name_to_provider_id.get(node_name, node_name)
            state = self.nodes.get(pid)
            if state is not None:
                state.cleanup_pod(*key)

    def _cleanup_old_bindings(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        old_node = self.bindings.get(key)
        if old_node is not None and old_node != pod.spec.node_name:
            pid = self.node_name_to_provider_id.get(old_node, old_node)
            state = self.nodes.get(pid)
            if state is not None:
                state.cleanup_pod(*key)
            del self.bindings[key]

    # -- daemonsets (cluster.go:339-375) -------------------------------------

    def update_csi_node(self, csi_node) -> None:
        """Hydrate per-driver attachable-volume limits onto the matching
        state node (CSINode is named after its Node)."""
        limits = {
            d.name: d.allocatable_count
            for d in csi_node.drivers
            if d.allocatable_count is not None
        }
        with self._mu:
            self._bump()
            self._csi_limits_by_node[csi_node.name] = limits
            pid = self.node_name_to_provider_id.get(csi_node.name)
            state = self.nodes.get(pid) if pid else None
            if state is not None:
                state.volume_usage.csi_limits = dict(limits)

    def delete_csi_node(self, name: str) -> None:
        with self._mu:
            self._bump()
            self._csi_limits_by_node.pop(name, None)
            pid = self.node_name_to_provider_id.get(name)
            state = self.nodes.get(pid) if pid else None
            if state is not None:
                state.volume_usage.csi_limits = {}

    def update_daemonset(self, daemonset: DaemonSet) -> None:
        with self._mu:
            self._bump()
            pod = Pod(spec=daemonset.pod_template_spec)
            pod.metadata.namespace = daemonset.namespace
            pod.metadata.name = f"{daemonset.name}-pod"
            self.daemonset_pods[(daemonset.namespace, daemonset.name)] = pod

    def delete_daemonset(self, namespace: str, name: str) -> None:
        with self._mu:
            self._bump()
            self.daemonset_pods.pop((namespace, name), None)

    def get_daemonset_pods(self) -> List[Pod]:
        with self._mu:
            return list(self.daemonset_pods.values())

    # -- consolidation timestamp (cluster.go:299-326) ------------------------

    def mark_unconsolidated(self) -> float:
        now = self.clock()
        # under the (reentrant) mutex: callers inside update paths already
        # hold it, but external callers (disruption controller) race the
        # watch threads without it
        with self._mu:
            self._consolidation_timestamp = now
        return now

    def consolidation_state(self) -> float:
        with self._mu:
            return self._consolidation_timestamp

    def reset(self) -> None:
        """Testing support (cluster.go:328). The generation counter must
        stay monotonic ACROSS resets: ``__init__`` would restart it at 0,
        and a warm solver whose seed cache was stamped at generation g
        would treat a post-reset cluster that mutated back up to g as
        unchanged — serving seed counts from the pre-reset world. The
        cache-invalidation analysis rule treats this direct write as the
        bump it is."""
        gen = self.generation()
        self.__init__(self.kube_client, self.cloud_provider, self.clock)
        with self._mu:
            self._generation = gen + 1
