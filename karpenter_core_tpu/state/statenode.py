"""StateNode: the merged Node + NodeClaim view (ref
pkg/controllers/state/statenode.go).

A node's identity during its lifecycle is (NodeClaim?, Node?) — the
claim exists first, the node joins later, and either can be missing for
unmanaged nodes. All scheduling reads go through this merged view.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import (
    COND_INITIALIZED,
    COND_REGISTERED,
    NodeClaim,
    NodeClaimSpec,
    NodeClaimStatus,
)
from ..kube.objects import (
    EFFECT_NO_SCHEDULE,
    Condition,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    ResourceList,
    Taint,
)
from ..scheduling import HostPortUsage, VolumeUsage, resources
from ..scheduling.taints import KNOWN_EPHEMERAL_TAINTS
from ..utils import pod as podutils

DISRUPTION_TAINT = podutils.DISRUPTION_NO_SCHEDULE_TAINT


# ---------------------------------------------------------------------------
# structural clones for deep_copy
#
# copy.deepcopy over the full Node/NodeClaim graphs dominated the
# consolidation profile (~70% of the 5k-candidate screen's wall time —
# every candidate/simulation pass copies the fleet). These hand-rolled
# clones copy exactly the containers the controllers mutate in place
# (metadata label/annotation/finalizer containers, taint LISTS,
# Condition objects — set_condition rewrites fields on the existing
# object — and capacity/allocatable dicts) and share everything treated
# as immutable after creation (Taint values, NodeSelectorRequirements,
# spec resources/kubelet refs, string/number leaves).


def _clone_meta(md):
    return ObjectMeta(
        name=md.name,
        namespace=md.namespace,
        uid=md.uid,
        labels=dict(md.labels),
        annotations=dict(md.annotations),
        finalizers=list(md.finalizers),
        owner_references=list(md.owner_references),
        creation_timestamp=md.creation_timestamp,
        deletion_timestamp=md.deletion_timestamp,
        resource_version=md.resource_version,
        generation=md.generation,
    )


def _clone_conditions(conds):
    return [
        Condition(
            type=c.type,
            status=c.status,
            reason=c.reason,
            message=c.message,
            last_transition_time=c.last_transition_time,
        )
        for c in conds
    ]


def _clone_node(n: Optional[Node]) -> Optional[Node]:
    if n is None:
        return None
    return Node(
        metadata=_clone_meta(n.metadata),
        spec=NodeSpec(
            provider_id=n.spec.provider_id,
            taints=list(n.spec.taints),
            unschedulable=n.spec.unschedulable,
        ),
        status=NodeStatus(
            capacity=dict(n.status.capacity),
            allocatable=dict(n.status.allocatable),
            conditions=_clone_conditions(n.status.conditions),
            phase=n.status.phase,
        ),
    )


def _clone_node_claim(c: Optional[NodeClaim]) -> Optional[NodeClaim]:
    if c is None:
        return None
    return NodeClaim(
        metadata=_clone_meta(c.metadata),
        spec=NodeClaimSpec(
            taints=list(c.spec.taints),
            startup_taints=list(c.spec.startup_taints),
            requirements=list(c.spec.requirements),
            resources=c.spec.resources,
            kubelet=c.spec.kubelet,
            node_class_ref=c.spec.node_class_ref,
        ),
        status=NodeClaimStatus(
            node_name=c.status.node_name,
            provider_id=c.status.provider_id,
            image_id=c.status.image_id,
            capacity=dict(c.status.capacity),
            allocatable=dict(c.status.allocatable),
            conditions=_clone_conditions(c.status.conditions),
        ),
    )


class StateNode:
    """statenode.go:78 — thread-safety is the Cluster's responsibility."""

    def __init__(self, node: Optional[Node] = None, node_claim: Optional[NodeClaim] = None):
        self.node = node
        self.node_claim = node_claim
        # pod key → requests (statenode.go pod tracking)
        self.pod_requests: Dict[tuple, ResourceList] = {}
        self.pod_limits: Dict[tuple, ResourceList] = {}
        self.daemonset_requests: Dict[tuple, ResourceList] = {}
        self.daemonset_limits: Dict[tuple, ResourceList] = {}
        self.host_port_usage = HostPortUsage()
        self.volume_usage = VolumeUsage()
        self.marked_for_deletion = False
        self.nominated_until: float = 0.0

    # -- identity ----------------------------------------------------------

    def name(self) -> str:
        """NodeClaim name until registered, then Node name (statenode.go:110)."""
        if self.node is None:
            return self.node_claim.name if self.node_claim else ""
        if not self.registered() and self.node_claim is not None:
            return self.node_claim.name
        return self.node.name

    def provider_id(self) -> str:
        if self.node is not None and self.node.spec.provider_id:
            return self.node.spec.provider_id
        if self.node_claim is not None:
            return self.node_claim.status.provider_id
        return ""

    def hostname(self) -> str:
        return self.labels().get(wk.LABEL_HOSTNAME, self.name())

    def managed(self) -> bool:
        """Managed by us ⇔ it has (or had) a NodeClaim / nodepool label."""
        if self.node_claim is not None:
            return True
        return self.node is not None and wk.NODEPOOL_LABEL_KEY in self.node.metadata.labels

    def nodepool_name(self) -> str:
        return self.labels().get(wk.NODEPOOL_LABEL_KEY, "")

    # -- merged views ------------------------------------------------------

    def labels(self) -> Dict[str, str]:
        """Node labels once registered, else claim labels (statenode.go:168)."""
        if not self.registered() and self.node_claim is not None:
            return dict(self.node_claim.metadata.labels)
        if self.node is None:
            return {}
        return dict(self.node.metadata.labels)

    def annotations(self) -> Dict[str, str]:
        if not self.registered() and self.node_claim is not None:
            return dict(self.node_claim.metadata.annotations)
        if self.node is None:
            return {}
        return dict(self.node.metadata.annotations)

    def taints(self) -> List[Taint]:
        """Effective taints; ephemeral startup taints and (pre-init) startup
        taints are ignored for scheduling (statenode.go:183-203)."""
        ephemeral: List[Taint] = list(KNOWN_EPHEMERAL_TAINTS)
        if not self.initialized() and self.managed() and self.node_claim is not None:
            ephemeral += self.node_claim.spec.startup_taints
        if (not self.registered() and self.node_claim is not None) or self.node is None:
            source = self.node_claim.spec.taints if self.node_claim else []
        else:
            source = self.node.spec.taints
        return [t for t in source if not any(t.match(e) and t.value == e.value for e in ephemeral)]

    def registered(self) -> bool:
        if self.managed():
            return (
                self.node is not None
                and self.node.metadata.labels.get(wk.NODE_REGISTERED_LABEL_KEY) == "true"
            )
        return self.node is not None

    def initialized(self) -> bool:
        if self.managed():
            return (
                self.node is not None
                and self.node.metadata.labels.get(wk.NODE_INITIALIZED_LABEL_KEY) == "true"
            )
        return self.node is not None

    def capacity(self) -> ResourceList:
        """Claim capacity until initialized (kubelet may under-report while
        starting), then node capacity (statenode.go:224)."""
        if not self.initialized() and self.node_claim is not None:
            if self.node_claim.status.capacity:
                return dict(self.node_claim.status.capacity)
        if self.node is None:
            return {}
        return dict(self.node.status.capacity)

    def allocatable(self) -> ResourceList:
        if not self.initialized() and self.node_claim is not None:
            if self.node_claim.status.allocatable:
                return dict(self.node_claim.status.allocatable)
        if self.node is None:
            return {}
        return dict(self.node.status.allocatable)

    def available(self) -> ResourceList:
        """Allocatable minus scheduled pod requests (statenode.go:259)."""
        return resources.subtract(self.allocatable(), self.pod_request_total())

    def pod_request_total(self) -> ResourceList:
        return resources.merge(*self.pod_requests.values()) if self.pod_requests else {}

    def pod_limit_total(self) -> ResourceList:
        return resources.merge(*self.pod_limits.values()) if self.pod_limits else {}

    def daemonset_request_total(self) -> ResourceList:
        return resources.merge(*self.daemonset_requests.values()) if self.daemonset_requests else {}

    def daemonset_limit_total(self) -> ResourceList:
        return resources.merge(*self.daemonset_limits.values()) if self.daemonset_limits else {}

    # -- nomination / deletion marks (statenode.go:311-340) ----------------

    def nominate(self, now: float, window: float = 20.0) -> None:
        self.nominated_until = now + window

    def nominated(self, now: float) -> bool:
        return self.nominated_until > now

    # -- pod bookkeeping (cluster.updateNodeUsageFromPod) ------------------

    def update_for_pod(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        self.pod_requests[key] = resources.requests_for_pods(pod)
        self.pod_limits[key] = resources.limits_for_pods(pod)
        if podutils.is_owned_by_daemonset(pod):
            self.daemonset_requests[key] = resources.requests_for_pods(pod)
            self.daemonset_limits[key] = resources.limits_for_pods(pod)
        from ..scheduling.hostports import get_host_ports

        self.host_port_usage.add(pod, get_host_ports(pod))

    def cleanup_pod(self, namespace: str, name: str) -> None:
        key = (namespace, name)
        self.pod_requests.pop(key, None)
        self.pod_limits.pop(key, None)
        self.daemonset_requests.pop(key, None)
        self.daemonset_limits.pop(key, None)
        self.host_port_usage.delete_pod(namespace, name)
        self.volume_usage.delete_pod(namespace, name)

    def deep_copy(self) -> "StateNode":
        out = StateNode(_clone_node(self.node), _clone_node_claim(self.node_claim))
        # flat copies sharing the VALUE dicts: every writer replaces a
        # key's value whole (update_for_pod assigns fresh ResourceLists,
        # cleanup_pod pops) and every reader merges/subtracts into new
        # dicts — values are immutable by discipline, so copying them
        # per node was pure waste (it dominated deep_copy_nodes at 500
        # nodes × 100 pods: ~200 ms/call before, ISSUE 7 profile)
        out.pod_requests = dict(self.pod_requests)
        out.pod_limits = dict(self.pod_limits)
        out.daemonset_requests = dict(self.daemonset_requests)
        out.daemonset_limits = dict(self.daemonset_limits)
        out.host_port_usage = self.host_port_usage.copy()
        out.volume_usage = self.volume_usage.copy()
        out.marked_for_deletion = self.marked_for_deletion
        out.nominated_until = self.nominated_until
        return out
