"""State informers: watch controllers feeding the Cluster cache (ref
pkg/controllers/state/informer/{node,pod,nodeclaim,nodepool,daemonset}.go,
plus CSINode for attach-limit hydration, volumeusage.go)."""

from __future__ import annotations

from ..kube import client as kube


class Informers:
    """Wires KubeClient watches to Cluster.Update*/Delete* — the
    reference's five thin controllers plus the CSINode watch."""

    def __init__(self, kube_client: kube.KubeClient, cluster) -> None:
        self.kube_client = kube_client
        self.cluster = cluster
        self._unsubscribes = []

    def start(self) -> None:
        self._unsubscribes = [
            self.kube_client.watch("Node", self._on_node),
            self.kube_client.watch("NodeClaim", self._on_node_claim),
            self.kube_client.watch("Pod", self._on_pod),
            self.kube_client.watch("DaemonSet", self._on_daemonset),
            self.kube_client.watch("NodePool", self._on_nodepool),
            self.kube_client.watch("CSINode", self._on_csi_node),
        ]

    def stop(self) -> None:
        for unsub in self._unsubscribes:
            unsub()
        self._unsubscribes = []

    # -- handlers ----------------------------------------------------------

    def _on_node(self, event: str, obj) -> None:
        if event == kube.DELETED:
            self.cluster.delete_node(obj.name)
        else:
            self.cluster.update_node(obj)

    def _on_node_claim(self, event: str, obj) -> None:
        if event == kube.DELETED:
            self.cluster.delete_node_claim(obj.name)
        else:
            self.cluster.update_node_claim(obj)

    def _on_pod(self, event: str, obj) -> None:
        if event == kube.DELETED:
            self.cluster.delete_pod(obj.namespace, obj.name)
        else:
            self.cluster.update_pod(obj)

    def _on_daemonset(self, event: str, obj) -> None:
        if event == kube.DELETED:
            self.cluster.delete_daemonset(obj.namespace, obj.name)
        else:
            self.cluster.update_daemonset(obj)

    def _on_nodepool(self, event: str, obj) -> None:
        # any nodepool change can open consolidation options
        # (informer/nodepool.go)
        self.cluster.mark_unconsolidated()

    def _on_csi_node(self, event: str, obj) -> None:
        if event == kube.DELETED:
            self.cluster.delete_csi_node(obj.name)
        else:
            self.cluster.update_csi_node(obj)
