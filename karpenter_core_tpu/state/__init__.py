from .statenode import StateNode
