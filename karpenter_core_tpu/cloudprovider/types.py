"""Cloud-provider SPI (ref pkg/cloudprovider/types.go).

This is the plugin seam: provider implementations translate NodeClaims
to real machines. The TPU tensorization layer consumes the
``InstanceType`` model behind this interface (capacity matrix, offering
availability/price tensors) without providers knowing about it.
"""

from __future__ import annotations

import abc
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import NodeClaim
from ..apis.nodepool import NodePool
from ..kube.objects import ResourceList
from ..scheduling import Requirements, resources
from ..utils.atomic import Lazy


@dataclass
class Offering:
    """Availability of an instance type in a (capacity type, zone), with
    price (types.go:127)."""

    capacity_type: str
    zone: str
    price: float
    available: bool = True


class Offerings(List[Offering]):
    def get(self, capacity_type: str, zone: str) -> Optional[Offering]:
        for o in self:
            if o.capacity_type == capacity_type and o.zone == zone:
                return o
        return None

    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def requirements(self, reqs: Requirements) -> "Offerings":
        """Offerings matching zone/capacity-type requirements (types.go:146)."""
        return Offerings(
            o
            for o in self
            if (not reqs.has(wk.LABEL_TOPOLOGY_ZONE) or reqs.get_req(wk.LABEL_TOPOLOGY_ZONE).has(o.zone))
            and (
                not reqs.has(wk.CAPACITY_TYPE_LABEL_KEY)
                or reqs.get_req(wk.CAPACITY_TYPE_LABEL_KEY).has(o.capacity_type)
            )
        )

    def cheapest(self) -> Optional[Offering]:
        return min(self, key=lambda o: o.price) if self else None


@dataclass
class InstanceTypeOverhead:
    kube_reserved: ResourceList = field(default_factory=dict)
    system_reserved: ResourceList = field(default_factory=dict)
    eviction_threshold: ResourceList = field(default_factory=dict)

    def total(self) -> ResourceList:
        return resources.merge(self.kube_reserved, self.system_reserved, self.eviction_threshold)


class InstanceType:
    """A potential node's properties (types.go:83), with memoized
    allocatable (types.go:104 precompute)."""

    __slots__ = ("name", "requirements", "offerings", "capacity", "overhead", "_allocatable")

    def __init__(
        self,
        name: str,
        requirements: Requirements,
        offerings: Offerings,
        capacity: ResourceList,
        overhead: Optional[InstanceTypeOverhead] = None,
    ):
        self.name = name
        self.requirements = requirements
        self.offerings = Offerings(offerings)
        self.capacity = capacity
        self.overhead = overhead or InstanceTypeOverhead()
        # thread-safe memoization (the cluster-state scrapers and solver
        # read catalogs from concurrent reconcilers)
        self._allocatable = Lazy(
            lambda: resources.subtract(self.capacity, self.overhead.total())
        )

    def allocatable(self) -> ResourceList:
        return dict(self._allocatable.get())

    # pickle support (solver/warmstore.py persists catalog entries): the
    # Lazy allocatable memo holds a lock and a closure — rebuild it on
    # load instead of serializing it
    def __getstate__(self) -> tuple:
        return (self.name, self.requirements, self.offerings, self.capacity, self.overhead)

    def __setstate__(self, state: tuple) -> None:
        self.__init__(*state)

    def __repr__(self) -> str:
        return f"InstanceType({self.name})"


def order_by_price(instance_types: List[InstanceType], reqs: Requirements) -> List[InstanceType]:
    """Sort by cheapest available offering matching reqs, ties by name
    (types.go:62 OrderByPrice)."""

    def key(it: InstanceType):
        matching = it.offerings.available().requirements(reqs)
        cheapest = matching.cheapest()
        return (cheapest.price if cheapest else math.inf, it.name)

    return sorted(instance_types, key=key)


# -- typed errors (types.go:169-256) ---------------------------------------


class CloudProviderError(Exception):
    pass


class NodeClaimNotFoundError(CloudProviderError):
    def __str__(self) -> str:
        return f"nodeclaim not found, {super().__str__()}"


class InsufficientCapacityError(CloudProviderError):
    def __str__(self) -> str:
        return f"insufficient capacity, {super().__str__()}"


class NodeClassNotReadyError(CloudProviderError):
    def __str__(self) -> str:
        return f"NodeClassRef not ready, {super().__str__()}"


class CloudProvider(abc.ABC):
    """Provider SPI (types.go:38-58)."""

    @abc.abstractmethod
    def create(self, node_claim: NodeClaim) -> NodeClaim:
        """Launch a machine for the claim; returns a hydrated claim with
        resolved labels/capacity/provider id."""

    @abc.abstractmethod
    def delete(self, node_claim: NodeClaim) -> None:
        """Terminate the machine backing the claim (NodeClaimNotFoundError
        if already gone)."""

    @abc.abstractmethod
    def get(self, provider_id: str) -> NodeClaim:
        """Retrieve a claim by provider id (NodeClaimNotFoundError if absent)."""

    @abc.abstractmethod
    def list(self) -> List[NodeClaim]:
        """All machines managed by this provider."""

    @abc.abstractmethod
    def get_instance_types(self, nodepool: Optional[NodePool]) -> List[InstanceType]:
        """All instance types (including unavailable offerings)."""

    @abc.abstractmethod
    def is_drifted(self, node_claim: NodeClaim) -> str:
        """Non-empty drift reason if the machine no longer matches its
        provisioning requirements."""

    @abc.abstractmethod
    def name(self) -> str:
        ...

    def catalog_generation(self, nodepool: Optional[NodePool] = None) -> Optional[int]:
        """Monotonic counter bumped on ANY catalog mutation (prices,
        capacities, offerings, requirements), or None when the provider
        doesn't maintain one. A non-None value lets the solver's
        cross-solve catalog/compat caches skip content fingerprinting —
        the provider then owns invalidation: serving a mutated catalog
        under an unbumped generation serves stale tensors."""
        return None
