"""Recording fake cloud provider + synthetic instance-type catalogs
(ref pkg/cloudprovider/fake/cloudprovider.go, instancetype.go).

Used by tests AND by the benchmark data generator — the synthetic
catalogs mirror the reference's so the performance grids are comparable.
"""

from __future__ import annotations

import copy
import itertools
import math
import threading
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..apis import labels as wk
from ..apis.nodeclaim import NodeClaim
from ..apis.nodepool import NodePool
from ..kube.objects import OP_DOES_NOT_EXIST, OP_IN, ResourceList
from ..kube.quantity import NANO, parse_quantity
from ..scheduling import Requirement, Requirements, resources
from ..scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    node_selector_requirements,
)
from .types import (
    CloudProvider,
    InstanceType,
    InstanceTypeOverhead,
    NodeClaimNotFoundError,
    Offering,
    Offerings,
)

# extra well-known labels the fake registers (fake/instancetype.go:34-47)
LABEL_INSTANCE_SIZE = "size"
EXOTIC_INSTANCE_LABEL_KEY = "special"
INTEGER_INSTANCE_LABEL_KEY = "integer"
RESOURCE_GPU_VENDOR_A = "fake.com/vendor-a"
RESOURCE_GPU_VENDOR_B = "fake.com/vendor-b"

_FAKE_LABELS = {LABEL_INSTANCE_SIZE, EXOTIC_INSTANCE_LABEL_KEY, INTEGER_INSTANCE_LABEL_KEY}


def register_fake_well_known_labels() -> None:
    """Register the fake's extra labels as well-known (the reference does
    this in a test-package init(), fake/instancetype.go:42-47). Called from
    the catalog constructors so merely importing this module doesn't change
    global label semantics."""
    wk.WELL_KNOWN_LABELS.update(_FAKE_LABELS)


def price_from_resources(res: ResourceList) -> float:
    """0.1/cpu + 0.1/GB mem + 1.0/gpu (fake/instancetype.go:177)."""
    price = 0.0
    for k, v in res.items():
        if k == "cpu":
            price += 0.1 * v / NANO
        elif k == "memory":
            price += 0.1 * (v / NANO) / 1e9
        elif k in (RESOURCE_GPU_VENDOR_A, RESOURCE_GPU_VENDOR_B):
            price += 1.0
    return price


def new_instance_type(
    name: str,
    resources_map: Optional[Dict[str, object]] = None,
    offerings: Optional[List[Offering]] = None,
    architecture: str = "amd64",
    operating_systems: Optional[List[str]] = None,
) -> InstanceType:
    """Synthetic instance type with the reference's defaulting
    (fake/instancetype.go:50 NewInstanceType)."""
    register_fake_well_known_labels()
    res: ResourceList = {k: parse_quantity(v) for k, v in (resources_map or {}).items()}
    res.setdefault("cpu", parse_quantity("4"))
    res.setdefault("memory", parse_quantity("4Gi"))
    res.setdefault("pods", parse_quantity("5"))
    if offerings is None:
        price = price_from_resources(res)
        offerings = [
            Offering("spot", "test-zone-1", price),
            Offering("spot", "test-zone-2", price),
            Offering("on-demand", "test-zone-1", price),
            Offering("on-demand", "test-zone-2", price),
            Offering("on-demand", "test-zone-3", price),
        ]
    operating_systems = operating_systems or ["linux", "windows", "darwin"]
    available = [o for o in offerings if o.available]
    cpu_whole = res["cpu"] // NANO
    reqs = Requirements(
        Requirement(wk.LABEL_INSTANCE_TYPE, OP_IN, [name]),
        Requirement(wk.LABEL_ARCH, OP_IN, [architecture]),
        Requirement(wk.LABEL_OS, OP_IN, operating_systems),
        Requirement(wk.LABEL_TOPOLOGY_ZONE, OP_IN, [o.zone for o in available]),
        Requirement(wk.CAPACITY_TYPE_LABEL_KEY, OP_IN, [o.capacity_type for o in available]),
        Requirement(LABEL_INSTANCE_SIZE, OP_DOES_NOT_EXIST),
        Requirement(EXOTIC_INSTANCE_LABEL_KEY, OP_DOES_NOT_EXIST),
        Requirement(INTEGER_INSTANCE_LABEL_KEY, OP_IN, [str(cpu_whole)]),
    )
    if res["cpu"] > parse_quantity("4") and res["memory"] > parse_quantity("8Gi"):
        reqs.get_req(LABEL_INSTANCE_SIZE).insert("large")
        reqs.get_req(EXOTIC_INSTANCE_LABEL_KEY).insert("optional")
    else:
        reqs.get_req(LABEL_INSTANCE_SIZE).insert("small")
    return InstanceType(
        name=name,
        requirements=reqs,
        offerings=Offerings(offerings),
        capacity=res,
        overhead=InstanceTypeOverhead(
            kube_reserved={"cpu": parse_quantity("100m"), "memory": parse_quantity("10Mi")}
        ),
    )


def instance_types(total: int) -> List[InstanceType]:
    """n types with incrementing resources: i → (i+1)vcpu, 2(i+1)Gi,
    10(i+1) pods (fake/instancetype.go:153 InstanceTypes)."""
    return [
        new_instance_type(
            f"fake-it-{i}",
            {"cpu": str(i + 1), "memory": f"{(i + 1) * 2}Gi", "pods": str((i + 1) * 10)},
        )
        for i in range(total)
    ]


def instance_types_assorted() -> List[InstanceType]:
    """Cross product of cpu×mem×zone×capacity×os×arch
    (fake/instancetype.go:112 InstanceTypesAssorted)."""
    out = []
    for cpu, mem, zone, ct, os_, arch in itertools.product(
        [1, 2, 4, 8, 16, 32, 64],
        [1, 2, 4, 8, 16, 32, 64, 128],
        ["test-zone-1", "test-zone-2", "test-zone-3"],
        [wk.CAPACITY_TYPE_SPOT, wk.CAPACITY_TYPE_ON_DEMAND],
        ["linux", "windows"],
        [wk.ARCHITECTURE_AMD64, wk.ARCHITECTURE_ARM64],
    ):
        res = {"cpu": str(cpu), "memory": f"{mem}Gi"}
        price = price_from_resources({k: parse_quantity(v) for k, v in res.items()})
        out.append(
            new_instance_type(
                f"{cpu}-cpu-{mem}-mem-{arch}-{os_}-{zone}-{ct}",
                res,
                offerings=[Offering(ct, zone, price)],
                architecture=arch,
                operating_systems=[os_],
            )
        )
    return out


def random_provider_id() -> str:
    return f"fake:///{uuid.uuid4().hex[:16]}"


class FakeCloudProvider(CloudProvider):
    """Recording fake (fake/cloudprovider.go:42)."""

    def __init__(self) -> None:
        self.instance_types: List[InstanceType] = []
        self.instance_types_for_nodepool: Dict[str, List[InstanceType]] = {}
        self.errors_for_nodepool: Dict[str, Exception] = {}
        self.create_calls: List[NodeClaim] = []
        self.delete_calls: List[NodeClaim] = []
        self.allowed_create_calls: int = 1 << 62
        self.next_create_err: Optional[Exception] = None
        self.next_delete_err: Optional[Exception] = None
        self.created_node_claims: Dict[str, NodeClaim] = {}
        self.drifted: str = "drifted"
        self._lock = threading.RLock()
        # catalog generation: None (default) = no signal, the solver
        # content-fingerprints each solve; once bump_catalog_generation()
        # is called the CALLER owns invalidation and must bump on every
        # in-place catalog mutation (bench.py's steady-state config does)
        self._catalog_generation: Optional[int] = None

    def reset(self) -> None:
        self.__init__()

    def catalog_generation(self, nodepool=None) -> Optional[int]:
        with self._lock:
            return self._catalog_generation

    def bump_catalog_generation(self) -> int:
        with self._lock:
            self._catalog_generation = (self._catalog_generation or 0) + 1
            return self._catalog_generation

    def _dirty_catalog(self) -> None:
        # callers hold self._lock. Only advances an ACTIVE generation:
        # while it is None the solver content-fingerprints every solve,
        # so plain-attribute mutation by older tests stays sound.
        if self._catalog_generation is not None:
            self._catalog_generation += 1

    def set_instance_types(self, instance_types: List[InstanceType]) -> None:
        """Replace the shared catalog. THE catalog mutator to use once
        ``bump_catalog_generation()`` activated the trusted-generation
        fast path: it advances the generation with the mutation, so the
        solver's catalog cache can never serve pre-mutation tensors
        (enforced by the cache-invalidation analysis rule)."""
        with self._lock:
            self.instance_types = list(instance_types)
            self._dirty_catalog()

    def set_instance_types_for_nodepool(
        self, nodepool_name: str, instance_types: List[InstanceType]
    ) -> None:
        """Per-pool catalog override, generation-correct like
        ``set_instance_types``."""
        with self._lock:
            self.instance_types_for_nodepool[nodepool_name] = list(instance_types)
            self._dirty_catalog()

    # -- SPI ----------------------------------------------------------------

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        with self._lock:
            if self.next_create_err is not None:
                err, self.next_create_err = self.next_create_err, None
                raise err
            self.create_calls.append(node_claim)
            if len(self.create_calls) > self.allowed_create_calls:
                raise RuntimeError("erroring as number of AllowedCreateCalls has been exceeded")
            reqs = node_selector_requirements(node_claim.spec.requirements)
            nodepool_name = node_claim.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            np = NodePool()
            np.metadata.name = nodepool_name
            candidates = [
                it
                for it in self.get_instance_types(np)
                if reqs.compatible(it.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS, hint=False) is None
                and len(it.offerings.requirements(reqs).available()) > 0
                and resources.fits(node_claim.spec.resources.requests, it.allocatable())
            ]
            if not candidates:
                from .types import InsufficientCapacityError

                raise InsufficientCapacityError(
                    f"no instance type satisfied requirements for nodeclaim {node_claim.name}"
                )
            candidates.sort(
                key=lambda it: it.offerings.available().requirements(reqs).cheapest().price
            )
            instance_type = candidates[0]
            labels = {}
            for key, req in instance_type.requirements.items():
                if req.operator() == OP_IN and len(req.values) == 1:
                    labels[key] = next(iter(req.values))
            for o in instance_type.offerings.available():
                offer_reqs = Requirements(
                    Requirement(wk.LABEL_TOPOLOGY_ZONE, OP_IN, [o.zone]),
                    Requirement(wk.CAPACITY_TYPE_LABEL_KEY, OP_IN, [o.capacity_type]),
                )
                if reqs.compatible(offer_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS, hint=False) is None:
                    labels[wk.LABEL_TOPOLOGY_ZONE] = o.zone
                    labels[wk.CAPACITY_TYPE_LABEL_KEY] = o.capacity_type
                    break
            created = copy.deepcopy(node_claim)
            created.metadata.labels = {**labels, **node_claim.metadata.labels}
            created.status.provider_id = random_provider_id()
            created.status.capacity = {k: v for k, v in instance_type.capacity.items() if v}
            created.status.allocatable = {k: v for k, v in instance_type.allocatable().items() if v}
            self.created_node_claims[created.status.provider_id] = created
            return created

    def get(self, provider_id: str) -> NodeClaim:
        with self._lock:
            nc = self.created_node_claims.get(provider_id)
            if nc is None:
                raise NodeClaimNotFoundError(f"no nodeclaim exists with provider id {provider_id}")
            return copy.deepcopy(nc)

    def list(self) -> List[NodeClaim]:
        with self._lock:
            return [copy.deepcopy(nc) for nc in self.created_node_claims.values()]

    def delete(self, node_claim: NodeClaim) -> None:
        with self._lock:
            if self.next_delete_err is not None:
                err, self.next_delete_err = self.next_delete_err, None
                raise err
            self.delete_calls.append(node_claim)
            if node_claim.status.provider_id in self.created_node_claims:
                del self.created_node_claims[node_claim.status.provider_id]
                return
            raise NodeClaimNotFoundError(
                f"no nodeclaim exists with provider id {node_claim.status.provider_id}"
            )

    def get_instance_types(self, nodepool: Optional[NodePool]) -> List[InstanceType]:
        with self._lock:
            if nodepool is not None:
                if nodepool.name in self.errors_for_nodepool:
                    raise self.errors_for_nodepool[nodepool.name]
                if nodepool.name in self.instance_types_for_nodepool:
                    return self.instance_types_for_nodepool[nodepool.name]
            if self.instance_types:
                return self.instance_types
        return [
            new_instance_type("default-instance-type"),
            new_instance_type("small-instance-type", {"cpu": 2, "memory": "2Gi"}),
            new_instance_type(
                "gpu-vendor-instance-type", {RESOURCE_GPU_VENDOR_A: 2}
            ),
            new_instance_type(
                "gpu-vendor-b-instance-type", {RESOURCE_GPU_VENDOR_B: 2}
            ),
            new_instance_type(
                "arm-instance-type",
                {"cpu": 16, "memory": "128Gi"},
                architecture="arm64",
                operating_systems=["ios", "linux", "windows", "darwin"],
            ),
            new_instance_type("single-pod-instance-type", {"pods": 1}),
        ]

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return self.drifted

    def name(self) -> str:
        return "fake"
