from .types import (
    CloudProvider,
    InstanceType,
    InstanceTypeOverhead,
    Offering,
    NodeClaimNotFoundError,
    InsufficientCapacityError,
    NodeClassNotReadyError,
    order_by_price,
)
