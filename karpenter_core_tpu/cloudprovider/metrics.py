"""CloudProvider metrics decorator (ref
pkg/cloudprovider/metrics/cloudprovider.go): wraps every SPI method with
duration + error counters."""

from __future__ import annotations

import time
from typing import List, Optional

from ..apis.nodeclaim import NodeClaim
from ..apis.nodepool import NodePool
from .types import CloudProvider, InstanceType


class MetricsDecorator(CloudProvider):
    def __init__(self, inner: CloudProvider, metrics):
        self.inner = inner
        self.metrics = metrics

    def _measure(self, method: str, fn):
        start = time.perf_counter()
        try:
            return fn()
        except Exception:
            self.metrics.cloudprovider_errors.inc(method=method, provider=self.inner.name())
            raise
        finally:
            self.metrics.cloudprovider_duration.observe(
                time.perf_counter() - start, method=method, provider=self.inner.name()
            )

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        return self._measure("Create", lambda: self.inner.create(node_claim))

    def delete(self, node_claim: NodeClaim) -> None:
        return self._measure("Delete", lambda: self.inner.delete(node_claim))

    def get(self, provider_id: str) -> NodeClaim:
        return self._measure("Get", lambda: self.inner.get(provider_id))

    def list(self) -> List[NodeClaim]:
        return self._measure("List", lambda: self.inner.list())

    def get_instance_types(self, nodepool: Optional[NodePool]) -> List[InstanceType]:
        return self._measure("GetInstanceTypes", lambda: self.inner.get_instance_types(nodepool))

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return self._measure("IsDrifted", lambda: self.inner.is_drifted(node_claim))

    def name(self) -> str:
        return self.inner.name()

    # passthrough for fakes' test hooks
    def __getattr__(self, item):
        return getattr(self.inner, item)
