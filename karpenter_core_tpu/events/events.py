"""Event constructors (refs: provisioning/scheduling/events.go,
disruption/events/events.go, node/terminator/events/events.go,
nodeclaim/lifecycle/events.go)."""

from __future__ import annotations

from .recorder import Event, NORMAL, WARNING


def pod_failed_to_schedule(pod, err) -> Event:
    return Event(
        involved_object=pod,
        type=WARNING,
        reason="FailedScheduling",
        message=f"Failed to schedule pod, {err}",
        dedupe_timeout=300.0,  # scheduling/events.go 5 min
        dedupe_values=(pod.namespace, pod.name, str(err)),
    )


def nominate_pod(pod, node_name) -> Event:
    return Event(
        involved_object=pod,
        type=NORMAL,
        reason="Nominated",
        message=f"Pod should schedule on: {node_name}",
        dedupe_values=(pod.namespace, pod.name, node_name),
    )


def disrupt_node(node, method, reason="") -> Event:
    return Event(
        involved_object=node,
        type=NORMAL,
        reason=f"Disrupt{method}",
        message=f"Disrupting node via {method} {reason}".strip(),
        dedupe_values=(node.name, method),
    )


def blocked(obj, reason: str, message: str) -> Event:
    return Event(
        involved_object=obj,
        type=NORMAL,
        reason=f"DisruptionBlocked",
        message=message,
        dedupe_values=(getattr(obj, "name", ""), reason),
    )


def evict(pod) -> Event:
    return Event(
        involved_object=pod,
        type=NORMAL,
        reason="Evicted",
        message="Evicted pod",
        dedupe_values=(pod.namespace, pod.name),
    )


def node_failed_to_drain(node, err) -> Event:
    return Event(
        involved_object=node,
        type=WARNING,
        reason="FailedDraining",
        message=f"Failed to drain node, {err}",
        dedupe_values=(node.name,),
    )


def insufficient_capacity(node_claim, err) -> Event:
    return Event(
        involved_object=node_claim,
        type=WARNING,
        reason="InsufficientCapacityError",
        message=f"NodeClaim {node_claim.name} event: {err}",
        dedupe_values=(node_claim.name,),
    )


def consistency_check_failed(obj, message: str) -> Event:
    return Event(
        involved_object=obj,
        type=WARNING,
        reason="FailedConsistencyCheck",
        message=message,
        dedupe_values=(getattr(obj, "name", ""), message),
    )
