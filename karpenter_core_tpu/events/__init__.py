from .recorder import Event, Recorder
