"""Dedup'd, rate-limited event recorder (ref pkg/events/recorder.go)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

NORMAL = "Normal"
WARNING = "Warning"

DEFAULT_DEDUPE_TIMEOUT = 120.0  # 2 min (recorder.go:35)


@dataclass
class Event:
    involved_object: object = None  # KubeObject
    type: str = NORMAL
    reason: str = ""
    message: str = ""
    dedupe_values: Tuple[str, ...] = ()
    dedupe_timeout: float = DEFAULT_DEDUPE_TIMEOUT
    rate_limit_per_minute: Optional[int] = None
    # solve-trace correlation: stamped at publish time from the active
    # trace (tracing/), so an event stream can be joined back to the
    # exact /debug/traces entry that produced it
    trace_id: str = ""

    def dedupe_key(self) -> tuple:
        if self.dedupe_values:
            return (self.reason,) + tuple(self.dedupe_values)
        obj = self.involved_object
        return (
            self.reason,
            self.message,
            getattr(obj, "kind", ""),
            getattr(obj, "namespace", ""),
            getattr(obj, "name", ""),
        )


class Recorder:
    """Publishes events with per-key dedupe (recorder.go:47-100). Events
    land in a ring buffer (and optionally the kube store) instead of a real
    apiserver."""

    def __init__(self, kube_client=None, clock: Callable[[], float] = time.time, capacity: int = 10000):
        self.kube_client = kube_client
        self.clock = clock
        self.capacity = capacity
        self.events: List[Event] = []
        self._seen: Dict[tuple, float] = {}
        self._rate: Dict[str, List[float]] = {}
        self._mu = threading.Lock()

    def publish(self, *events: Event) -> None:
        for e in events:
            self._publish_one(e)

    def _publish_one(self, e: Event) -> None:
        if e is None:
            return
        if not e.trace_id:
            from ..tracing.tracer import current_trace_id

            e.trace_id = current_trace_id() or ""
        now = self.clock()
        with self._mu:
            key = e.dedupe_key()
            last = self._seen.get(key)
            if last is not None and now - last < e.dedupe_timeout:
                return
            if e.rate_limit_per_minute is not None:
                window = [t for t in self._rate.get(e.reason, []) if now - t < 60.0]
                if len(window) >= e.rate_limit_per_minute:
                    self._rate[e.reason] = window
                    return
                window.append(now)
                self._rate[e.reason] = window
            self._seen[key] = now
            self.events.append(e)
            if len(self.events) > self.capacity:
                self.events = self.events[-self.capacity :]

    # test helpers (mirrors pkg/test/expectations event assertions)
    def reasons(self) -> List[str]:
        with self._mu:
            return [e.reason for e in self.events]

    def find(self, reason: str) -> List[Event]:
        with self._mu:
            return [e for e in self.events if e.reason == reason]

    def reset(self) -> None:
        with self._mu:
            self.events.clear()
            self._seen.clear()
            self._rate.clear()
