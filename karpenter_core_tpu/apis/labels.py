"""Well-known labels, annotations and domains (ref pkg/apis/v1beta1/labels.go)."""

from __future__ import annotations

GROUP = "karpenter.sh"
COMPATIBILITY_GROUP = "compatibility.karpenter.sh"

# kubernetes well-known label keys (k8s.io/api/core/v1 constants)
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
LABEL_TOPOLOGY_REGION = "topology.kubernetes.io/region"
LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
LABEL_ARCH = "kubernetes.io/arch"
LABEL_OS = "kubernetes.io/os"
LABEL_WINDOWS_BUILD = "node.kubernetes.io/windows-build"
LABEL_FAILURE_DOMAIN_BETA_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_FAILURE_DOMAIN_BETA_REGION = "failure-domain.beta.kubernetes.io/region"
LABEL_INSTANCE_TYPE_BETA = "beta.kubernetes.io/instance-type"

ARCHITECTURE_AMD64 = "amd64"
ARCHITECTURE_ARM64 = "arm64"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"

# karpenter domains/labels (labels.go:36-41)
NODEPOOL_LABEL_KEY = f"{GROUP}/nodepool"
NODE_INITIALIZED_LABEL_KEY = f"{GROUP}/initialized"
NODE_REGISTERED_LABEL_KEY = f"{GROUP}/registered"
CAPACITY_TYPE_LABEL_KEY = f"{GROUP}/capacity-type"

# annotations (labels.go:44-49)
DO_NOT_DISRUPT_ANNOTATION_KEY = f"{GROUP}/do-not-disrupt"
MANAGED_BY_ANNOTATION_KEY = f"{GROUP}/managed-by"
NODEPOOL_HASH_ANNOTATION_KEY = f"{GROUP}/nodepool-hash"

# v1alpha5 compat (ref pkg/apis/v1alpha5/labels.go, used at
# disruption/consolidation.go:98)
DO_NOT_CONSOLIDATE_ANNOTATION_KEY = "karpenter.sh/do-not-consolidate"
DO_NOT_EVICT_ANNOTATION_KEY = "karpenter.sh/do-not-evict"

# finalizers (labels.go:52-54)
TERMINATION_FINALIZER = f"{GROUP}/termination"

# taints
DISRUPTION_TAINT_KEY = f"{GROUP}/disruption"
DISRUPTION_NO_SCHEDULE_VALUE = "disrupting"
REGISTRATION_TAINT_KEY = f"{GROUP}/registered"  # karpenter.sh/registered:NoExecute until registered

# node lifecycle taints kubelet applies (ref pkg/scheduling/taints.go:28-32)
TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_EXTERNAL_CLOUD_PROVIDER = "node.cloudprovider.kubernetes.io/uninitialized"

RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", GROUP})

LABEL_DOMAIN_EXCEPTIONS = frozenset(
    {"kops.k8s.io", "node.kubernetes.io", "node-restriction.kubernetes.io"}
)

# mutable: cloud providers may register additional well-known labels at
# import time (the reference's fake does this in init(),
# fake/instancetype.go:42-47)
WELL_KNOWN_LABELS = {
    NODEPOOL_LABEL_KEY,
    LABEL_TOPOLOGY_ZONE,
    LABEL_TOPOLOGY_REGION,
    LABEL_INSTANCE_TYPE,
    LABEL_ARCH,
    LABEL_OS,
    CAPACITY_TYPE_LABEL_KEY,
    LABEL_WINDOWS_BUILD,
}

RESTRICTED_LABELS = frozenset({LABEL_HOSTNAME})

# aliased → canonical label keys (labels.go:94-100)
NORMALIZED_LABELS = {
    LABEL_FAILURE_DOMAIN_BETA_ZONE: LABEL_TOPOLOGY_ZONE,
    "beta.kubernetes.io/arch": LABEL_ARCH,
    "beta.kubernetes.io/os": LABEL_OS,
    LABEL_INSTANCE_TYPE_BETA: LABEL_INSTANCE_TYPE,
    LABEL_FAILURE_DOMAIN_BETA_REGION: LABEL_TOPOLOGY_REGION,
}


def get_label_domain(key: str) -> str:
    if "/" in key:
        return key.split("/", 1)[0]
    return ""


def is_restricted_node_label(key: str) -> bool:
    """True if karpenter must not inject this label onto nodes (labels.go:117-133)."""
    if key in WELL_KNOWN_LABELS:
        return True
    domain = get_label_domain(key)
    for exc in LABEL_DOMAIN_EXCEPTIONS:
        if domain.endswith(exc):
            return False
    for restricted in RESTRICTED_LABEL_DOMAINS:
        if domain.endswith(restricted):
            return True
    return key in RESTRICTED_LABELS


def is_restricted_label(key: str) -> str | None:
    """Returns an error message if the label may not be used (labels.go:104-112)."""
    if key in WELL_KNOWN_LABELS:
        return None
    if is_restricted_node_label(key):
        return (
            f"label {key} is restricted; specify a well known label "
            f"or a custom label that does not use a restricted domain"
        )
    return None
