"""Admission validation for NodePool / NodeClaim specs.

Re-expresses the reference's two validation layers in one place:
the CEL rules stamped on the CRDs (ref pkg/apis/v1beta1/nodepool.go:42-43,
53-54, 63-114 kubebuilder markers) and the webhook/runtime validation
(ref nodepool_validation.go:35-111, nodeclaim_validation.go:71-276).
Errors are collected as strings (field-path prefixed) rather than raised
one at a time, mirroring knative's accumulated FieldError.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..kube.objects import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    NodeSelectorRequirement,
    Taint,
)
from ..kube.quantity import parse_quantity
from . import labels as lbl
from .nodeclaim import KubeletConfiguration, NodeClaim, NodeClaimSpec
from .nodepool import (
    CONSOLIDATION_POLICY_WHEN_EMPTY,
    CONSOLIDATION_POLICY_WHEN_UNDERUTILIZED,
    Budget,
    Disruption,
    NodePool,
)

# ref nodeclaim_validation.go:37-44
SUPPORTED_NODE_SELECTOR_OPS = frozenset(
    {"In", "NotIn", "Gt", "Lt", "Exists", "DoesNotExist"}
)
# ref nodeclaim_validation.go:46-51
SUPPORTED_RESERVED_RESOURCES = frozenset({"cpu", "memory", "ephemeral-storage", "pid"})
# ref nodeclaim_validation.go:53-60
SUPPORTED_EVICTION_SIGNALS = frozenset(
    {
        "memory.available",
        "nodefs.available",
        "nodefs.inodesFree",
        "imagefs.available",
        "imagefs.inodesFree",
        "pid.available",
    }
)

_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_DNS1123_SUBDOMAIN = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$"
)
_QUALIFIED_NAME_PART = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")
_LABEL_VALUE = re.compile(r"^([A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?)?$")
# ref nodepool.go:108 crontab CEL pattern (anchored as one alternation; the
# reference's raw pattern is effectively unanchored on the macro side)
_CRONTAB = re.compile(
    r"^(@(annually|yearly|monthly|weekly|daily|midnight|hourly)"
    r"|(\S+)\s+(\S+)\s+(\S+)\s+(\S+)\s+(\S+))$"
)


class ValidationError(Exception):
    """Raised by validate-or-die entry points; carries all field errors."""

    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


# ---------------------------------------------------------------------------
# k8s.io/apimachinery/pkg/util/validation semantics


def is_qualified_name(key: str) -> List[str]:
    """IsQualifiedName: optional DNS-1123 subdomain prefix '/', then a
    63-char qualified name part."""
    errs: List[str] = []
    parts = key.split("/")
    if len(parts) == 1:
        name = parts[0]
    elif len(parts) == 2:
        prefix, name = parts
        if not prefix:
            errs.append("prefix part must be non-empty")
        elif len(prefix) > 253 or not _DNS1123_SUBDOMAIN.match(prefix):
            errs.append("prefix part must be a valid DNS-1123 subdomain")
    else:
        errs.append("a qualified name must have at most one '/'")
        return errs
    if not name:
        errs.append("name part must be non-empty")
    elif len(name) > 63 or not _QUALIFIED_NAME_PART.match(name):
        errs.append(
            "name part must consist of alphanumeric characters, '-', '_' or '.', "
            "and must start and end with an alphanumeric character"
        )
    return errs


def is_valid_label_value(value: str) -> List[str]:
    if len(value) > 63 or not _LABEL_VALUE.match(value):
        return [
            "a valid label value must be an empty string or consist of alphanumeric "
            "characters, '-', '_' or '.', and must start and end with an "
            "alphanumeric character"
        ]
    return []


def is_dns1123_subdomain(value: str) -> List[str]:
    if len(value) > 253 or not _DNS1123_SUBDOMAIN.match(value):
        return ["must be a valid DNS-1123 subdomain"]
    return []


# ---------------------------------------------------------------------------
# requirement validation (ref nodeclaim_validation.go:144-177)


def validate_requirement(req: NodeSelectorRequirement) -> List[str]:
    errs: List[str] = []
    key = lbl.NORMALIZED_LABELS.get(req.key, req.key)
    if req.operator not in SUPPORTED_NODE_SELECTOR_OPS:
        errs.append(
            f"key {key} has an unsupported operator {req.operator} "
            f"not in {sorted(SUPPORTED_NODE_SELECTOR_OPS)}"
        )
    msg = lbl.is_restricted_label(key)
    if msg is not None:
        errs.append(msg)
    for e in is_qualified_name(key):
        errs.append(f"key {key} is not a qualified name, {e}")
    for value in req.values:
        for e in is_valid_label_value(value):
            errs.append(f"invalid value {value} for key {key}, {e}")
    if req.operator == "In" and not req.values:
        errs.append(f"key {key} with operator In must have a value defined")
    if req.operator in ("Gt", "Lt"):
        ok = len(req.values) == 1
        if ok:
            try:
                ok = int(req.values[0]) >= 0
            except ValueError:
                ok = False
        if not ok:
            errs.append(
                f"key {key} with operator {req.operator} must have a single "
                f"positive integer value"
            )
    return errs


# ---------------------------------------------------------------------------
# taint validation (ref nodeclaim_validation.go:91-130)

_VALID_EFFECTS = (EFFECT_NO_SCHEDULE, EFFECT_PREFER_NO_SCHEDULE, EFFECT_NO_EXECUTE, "")


def _validate_taints_field(
    taints: List[Taint],
    existing: Dict[Tuple[str, str], bool],
    field_name: str,
) -> List[str]:
    errs: List[str] = []
    for i, taint in enumerate(taints):
        if not taint.key:
            errs.append(f"{field_name}[{i}]: taint key must be non-empty")
        else:
            for e in is_qualified_name(taint.key):
                errs.append(f"{field_name}[{i}]: invalid key {taint.key}, {e}")
        if taint.value:
            # the reference webhook checks IsQualifiedName here
            # (nodeclaim_validation.go:110), but the apiserver's own taint
            # validation uses label-value semantics — enforce the stricter
            # form so stamped taints survive a real apiserver
            for e in is_valid_label_value(taint.value):
                errs.append(f"{field_name}[{i}]: invalid value {taint.value}, {e}")
        if taint.effect not in _VALID_EFFECTS:
            errs.append(f"{field_name}[{i}]: invalid effect {taint.effect}")
        pair = (taint.key, taint.effect)
        if pair in existing:
            errs.append(
                f"{field_name}[{i}]: duplicate taint Key/Effect pair "
                f"{taint.key}={taint.effect}"
            )
        existing[pair] = True
    return errs


def validate_taints(spec: NodeClaimSpec | "object") -> List[str]:
    """Duplicate detection spans taints AND startupTaints
    (nodeclaim_validation.go:91-96)."""
    existing: Dict[Tuple[str, str], bool] = {}
    errs = _validate_taints_field(spec.taints, existing, "taints")
    errs += _validate_taints_field(spec.startup_taints, existing, "startupTaints")
    return errs


# ---------------------------------------------------------------------------
# kubelet configuration (ref nodeclaim_validation.go:179-276)


def validate_kubelet(k: Optional[KubeletConfiguration]) -> List[str]:
    if k is None:
        return []
    errs: List[str] = []
    for field_name, m in (
        ("evictionHard", k.eviction_hard),
        ("evictionSoft", k.eviction_soft),
    ):
        for sig, v in m.items():
            if sig not in SUPPORTED_EVICTION_SIGNALS:
                errs.append(f"{field_name}[{sig}]: unsupported eviction signal")
            if v.endswith("%"):
                try:
                    p = float(v.rstrip("%"))
                except ValueError:
                    errs.append(f"{field_name}[{sig}]: {v} is not a valid percentage")
                    continue
                if p < 0:
                    errs.append(f"{field_name}[{sig}]: percentage cannot be negative")
                if p > 100:
                    errs.append(
                        f"{field_name}[{sig}]: percentage cannot be greater than 100"
                    )
            else:
                try:
                    parse_quantity(v)
                except Exception:
                    errs.append(
                        f"{field_name}[{sig}]: {v} could not be parsed as a quantity"
                    )
    for field_name, m in (
        ("kubeReserved", k.kube_reserved),
        ("systemReserved", k.system_reserved),
    ):
        for res, qty in m.items():
            if res not in SUPPORTED_RESERVED_RESOURCES:
                errs.append(f"{field_name}[{res}]: unsupported reserved resource")
            if qty < 0:
                errs.append(f"{field_name}[{res}]: cannot be a negative quantity")
    soft = set(k.eviction_soft)
    grace = set(k.eviction_soft_grace_period)
    for sig in k.eviction_soft_grace_period:
        if sig not in SUPPORTED_EVICTION_SIGNALS:
            errs.append(f"evictionSoftGracePeriod[{sig}]: unsupported eviction signal")
    for sig in soft - grace:
        errs.append(
            f"evictionSoft[{sig}]: key does not have a matching evictionSoftGracePeriod"
        )
    for sig in grace - soft:
        errs.append(
            f"evictionSoftGracePeriod[{sig}]: key does not have a matching "
            f"evictionSoft threshold value"
        )
    if (
        k.image_gc_high_threshold_percent is not None
        and k.image_gc_low_threshold_percent is not None
        and k.image_gc_high_threshold_percent < k.image_gc_low_threshold_percent
    ):
        errs.append(
            "imageGCHighThresholdPercent: must be greater than "
            "imageGCLowThresholdPercent"
        )
    return errs


# ---------------------------------------------------------------------------
# disruption + budgets (ref nodepool_validation.go:97-111 + CEL
# nodepool.go:42-43,88,108,114)


def validate_disruption(d: Disruption) -> List[str]:
    errs: List[str] = []
    if d.expire_after is not None and d.expire_after < 0:
        errs.append("disruption.expireAfter: cannot be negative")
    if d.consolidate_after is not None and d.consolidate_after < 0:
        errs.append("disruption.consolidateAfter: cannot be negative")
    if d.consolidation_policy not in (
        CONSOLIDATION_POLICY_WHEN_EMPTY,
        CONSOLIDATION_POLICY_WHEN_UNDERUTILIZED,
    ):
        errs.append(
            f"disruption.consolidationPolicy: unsupported value "
            f"{d.consolidation_policy}"
        )
    if (
        d.consolidate_after is not None
        and d.consolidation_policy == CONSOLIDATION_POLICY_WHEN_UNDERUTILIZED
    ):
        errs.append(
            "disruption: consolidateAfter cannot be combined with "
            "consolidationPolicy=WhenUnderutilized"
        )
    if (
        d.consolidate_after is None
        and d.consolidation_policy == CONSOLIDATION_POLICY_WHEN_EMPTY
    ):
        errs.append(
            "disruption: consolidateAfter must be specified with "
            "consolidationPolicy=WhenEmpty"
        )
    if len(d.budgets) > 50:
        errs.append("disruption.budgets: must have at most 50 items")
    for i, b in enumerate(d.budgets):
        errs += [f"disruption.budgets[{i}]: {e}" for e in validate_budget(b)]
    return errs


def validate_budget(b: Budget) -> List[str]:
    errs: List[str] = []
    nodes = b.nodes
    if nodes.endswith("%"):
        try:
            p = int(nodes[:-1])
        except ValueError:
            p = -1
        if not (0 <= p <= 100):
            errs.append(f"nodes: {nodes} must be a percentage in [0%, 100%]")
    else:
        try:
            if int(nodes) < 0:
                errs.append(f"nodes: {nodes} cannot be negative")
        except ValueError:
            errs.append(f"nodes: {nodes} must be an integer or percentage")
    # 'crontab' must be set with 'duration' and vice versa (nodepool.go:88)
    if (b.schedule is None) != (b.duration is None):
        errs.append("crontab must be set with duration")
    if b.schedule is not None and not _CRONTAB.match(b.schedule):
        errs.append(f"crontab: {b.schedule} is not a valid cron schedule")
    if b.duration is not None and b.duration < 0:
        errs.append("duration: cannot be negative")
    return errs


# ---------------------------------------------------------------------------
# object-level entry points


def validate_nodeclaim_spec(spec: NodeClaimSpec) -> List[str]:
    errs = validate_taints(spec)
    for i, req in enumerate(spec.requirements):
        errs += [f"requirements[{i}]: {e}" for e in validate_requirement(req)]
    errs += [f"kubeletConfiguration: {e}" for e in validate_kubelet(spec.kubelet)]
    return errs


def validate_nodeclaim(nc: NodeClaim) -> List[str]:
    errs = [f"metadata.name: {e}" for e in is_dns1123_subdomain(nc.name)]
    errs += [f"spec: {e}" for e in validate_nodeclaim_spec(nc.spec)]
    return errs


def validate_template_labels(template_labels: Dict[str, str]) -> List[str]:
    """ref nodepool_validation.go:70-86."""
    errs: List[str] = []
    for key, value in template_labels.items():
        if key == lbl.NODEPOOL_LABEL_KEY:
            errs.append(f"labels[{key}]: restricted")
            continue
        for e in is_qualified_name(key):
            errs.append(f"labels[{key}]: invalid key, {e}")
        for e in is_valid_label_value(value):
            errs.append(f"labels[{key}]: invalid value {value}, {e}")
        msg = lbl.is_restricted_label(key)
        if msg is not None:
            errs.append(f"labels[{key}]: {msg}")
    return errs


def validate_nodepool(np: NodePool) -> List[str]:
    """Full admission validation = CRD-level + RuntimeValidate
    (nodepool_validation.go:35-50)."""
    errs = [f"metadata.name: {e}" for e in is_dns1123_subdomain(np.name)]
    t = np.spec.template
    errs += [f"spec.template.metadata: {e}" for e in validate_template_labels(t.metadata.labels)]
    errs += [f"spec.template.spec: {e}" for e in validate_taints(t)]
    for i, req in enumerate(t.requirements):
        errs += [
            f"spec.template.spec.requirements[{i}]: {e}"
            for e in validate_requirement(req)
        ]
        # the nodepool label is stamped by the controller, never user-set
        # (nodepool_validation.go:88-95)
        if req.key == lbl.NODEPOOL_LABEL_KEY:
            errs.append(
                f"spec.template.spec.requirements[{i}]: "
                f"{lbl.NODEPOOL_LABEL_KEY} is restricted"
            )
    errs += [f"spec.template.spec.kubeletConfiguration: {e}" for e in validate_kubelet(t.kubelet)]
    errs += [f"spec: {e}" for e in validate_disruption(np.spec.disruption)]
    if np.spec.weight is not None and not (1 <= np.spec.weight <= 100):
        errs.append("spec.weight: must be in [1, 100]")  # nodepool.go:53-54
    for res, qty in np.spec.limits.items():
        if qty < 0:
            errs.append(f"spec.limits[{res}]: cannot be negative")
    return errs


def validate_or_die(obj) -> None:
    """Admission seam: raise ValidationError with all accumulated errors."""
    if isinstance(obj, NodePool):
        errs = validate_nodepool(obj)
    elif isinstance(obj, NodeClaim):
        errs = validate_nodeclaim(obj)
    else:
        return
    if errs:
        raise ValidationError(errs)


def install_admission(client) -> None:
    """Register defaulting + validating admission on a KubeClient — the
    stand-in for the reference's webhook registration
    (webhooks.go:57-87, disabled-by-default there; on by default here
    since CEL enforcement is otherwise absent in-process)."""
    client.admission.append(set_defaults)
    client.admission.append(validate_or_die)


def set_defaults(obj) -> None:
    """ref nodepool_defaults.go / nodeclaim_defaults.go: SetDefaults are
    no-ops in v1beta1 (defaulting happens via CRD markers); the one
    live default is the 10% disruption budget (nodepool.go:89)."""
    if isinstance(obj, NodePool) and not obj.spec.disruption.budgets:
        obj.spec.disruption.budgets = [Budget(nodes="10%")]
