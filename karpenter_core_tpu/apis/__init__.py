from . import labels
from .nodepool import NodePool, NodePoolSpec, NodeClaimTemplateSpec, Disruption, Budget
from .nodeclaim import NodeClaim, NodeClaimSpec, NodeClaimStatus
