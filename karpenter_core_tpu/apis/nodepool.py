"""NodePool API type (ref pkg/apis/v1beta1/nodepool.go)."""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..kube.objects import (
    KubeObject,
    NodeSelectorRequirement,
    ResourceList,
    Taint,
)
from .nodeclaim import KubeletConfiguration, NodeClassReference

CONSOLIDATION_POLICY_WHEN_EMPTY = "WhenEmpty"
CONSOLIDATION_POLICY_WHEN_UNDERUTILIZED = "WhenUnderutilized"


@dataclass
class Budget:
    """Disruption budget (nodepool.go:97-118): at most ``nodes`` (count or
    percent string like "10%") may be disrupting at once while active."""

    nodes: str = "10%"
    schedule: Optional[str] = None  # crontab; None = always active
    duration: Optional[float] = None  # seconds the budget is active per crontab hit


@dataclass
class Disruption:
    """NodePool disruption policy (nodepool.go:59-92)."""

    consolidate_after: Optional[float] = None  # seconds; None = immediately eligible
    consolidation_policy: str = CONSOLIDATION_POLICY_WHEN_UNDERUTILIZED
    expire_after: Optional[float] = None  # seconds; None = Never
    budgets: List[Budget] = field(default_factory=list)


@dataclass
class NodeClaimTemplateObjectMeta:
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)


@dataclass
class NodeClaimTemplateSpec:
    """Template stamped onto NodeClaims (nodepool.go:143-147)."""

    metadata: NodeClaimTemplateObjectMeta = field(default_factory=NodeClaimTemplateObjectMeta)
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    requirements: List[NodeSelectorRequirement] = field(default_factory=list)
    kubelet: Optional[KubeletConfiguration] = None
    node_class_ref: Optional[NodeClassReference] = None


@dataclass
class NodePoolSpec:
    template: NodeClaimTemplateSpec = field(default_factory=NodeClaimTemplateSpec)
    disruption: Disruption = field(default_factory=Disruption)
    limits: ResourceList = field(default_factory=dict)  # nodepool.go:127
    weight: Optional[int] = None  # 1-100, higher = tried first (nodepool.go:56)


@dataclass
class NodePoolStatus:
    resources: ResourceList = field(default_factory=dict)


@dataclass
class NodePool(KubeObject):
    spec: NodePoolSpec = field(default_factory=NodePoolSpec)
    status: NodePoolStatus = field(default_factory=NodePoolStatus)

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    def static_hash(self) -> str:
        """Hash of the disruption-relevant static spec fields (nodepool.go:179,
        `hash:"ignore"` on requirements/resources/budgets). Used by the hash
        controller and drift detection."""
        t = self.spec.template
        payload = {
            "labels": sorted(t.metadata.labels.items()),
            "annotations": sorted(t.metadata.annotations.items()),
            "taints": sorted((x.key, x.value, x.effect) for x in t.taints),
            "startup_taints": sorted((x.key, x.value, x.effect) for x in t.startup_taints),
            "kubelet": _kubelet_repr(t.kubelet),
            "node_class_ref": (
                (t.node_class_ref.name, t.node_class_ref.kind, t.node_class_ref.api_version)
                if t.node_class_ref
                else None
            ),
        }
        return hashlib.sha256(json.dumps(payload, sort_keys=True, default=str).encode()).hexdigest()[:16]


def _kubelet_repr(k: Optional[KubeletConfiguration]):
    if k is None:
        return None
    return (
        k.max_pods,
        k.pods_per_core,
        sorted(k.system_reserved.items()),
        sorted(k.kube_reserved.items()),
        sorted(k.eviction_hard.items()),
        sorted(k.eviction_soft.items()),
    )


def order_by_weight(nodepools: List[NodePool]) -> List[NodePool]:
    """Highest weight first, ties by name (nodepool.go:197 OrderByWeight)."""
    return sorted(nodepools, key=lambda np: (-(np.spec.weight or 0), np.name))
