"""NodeClaim API type (ref pkg/apis/v1beta1/nodeclaim.go, nodeclaim_status.go)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..kube.objects import (
    Condition,
    KubeObject,
    NodeSelectorRequirement,
    ResourceList,
    Taint,
)

# status condition types (nodeclaim_status.go:60-66)
COND_LAUNCHED = "Launched"
COND_REGISTERED = "Registered"
COND_INITIALIZED = "Initialized"
COND_EMPTY = "Empty"
COND_DRIFTED = "Drifted"
COND_EXPIRED = "Expired"


@dataclass
class NodeClassReference:
    """Provider-specific config reference (nodeclaim.go:134-144)."""

    name: str = ""
    kind: str = ""
    api_version: str = ""


@dataclass
class KubeletConfiguration:
    """Kubelet args for provisioned nodes (nodeclaim.go:70-131); the subset
    that affects scheduling math (maxPods / reserved resources)."""

    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    system_reserved: ResourceList = field(default_factory=dict)
    kube_reserved: ResourceList = field(default_factory=dict)
    eviction_hard: Dict[str, str] = field(default_factory=dict)
    eviction_soft: Dict[str, str] = field(default_factory=dict)
    # seconds per eviction signal (nodeclaim.go:110, metav1.Duration map)
    eviction_soft_grace_period: Dict[str, float] = field(default_factory=dict)
    eviction_max_pod_grace_period: Optional[int] = None
    image_gc_high_threshold_percent: Optional[int] = None  # nodeclaim.go:119-124
    image_gc_low_threshold_percent: Optional[int] = None
    cpu_cfs_quota: Optional[bool] = None  # nodeclaim.go:129-131
    cluster_dns: List[str] = field(default_factory=list)


@dataclass
class NodeClaimResources:
    requests: ResourceList = field(default_factory=dict)


@dataclass
class NodeClaimSpec:
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    requirements: List[NodeSelectorRequirement] = field(default_factory=list)
    resources: NodeClaimResources = field(default_factory=NodeClaimResources)
    kubelet: Optional[KubeletConfiguration] = None
    node_class_ref: Optional[NodeClassReference] = None


@dataclass
class NodeClaimStatus:
    node_name: str = ""
    provider_id: str = ""
    image_id: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class NodeClaim(KubeObject):
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped

    # -- condition helpers (apis.ConditionType machinery in the reference) --

    def get_condition(self, cond_type: str) -> Optional[Condition]:
        for c in self.status.conditions:
            if c.type == cond_type:
                return c
        return None

    def status_condition_is_true(self, cond_type: str) -> bool:
        c = self.get_condition(cond_type)
        return c is not None and c.status == "True"

    def set_condition(self, cond_type: str, status: str = "True", reason: str = "", message: str = "") -> None:
        existing = self.get_condition(cond_type)
        if existing is not None:
            if existing.status != status:
                existing.last_transition_time = time.time()
            existing.status = status
            existing.reason = reason
            existing.message = message
        else:
            self.status.conditions.append(
                Condition(type=cond_type, status=status, reason=reason, message=message)
            )

    def clear_condition(self, cond_type: str) -> None:
        self.status.conditions = [c for c in self.status.conditions if c.type != cond_type]
