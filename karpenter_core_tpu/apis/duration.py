"""NillableDuration (ref pkg/apis/v1beta1/duration.go).

A duration that can be explicitly ``Never`` (nil in the Go API). We
represent durations as float seconds; ``None`` means "never".
"""

from __future__ import annotations

import re
from typing import Optional

_UNITS = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}
_TOKEN = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")


def parse_duration(value) -> Optional[float]:
    """Parse a Go duration string ("15m", "1h30m", "Never") to seconds."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    s = value.strip()
    if s in ("Never", "never", ""):
        return None
    total, pos = 0.0, 0
    for m in _TOKEN.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {value!r}")
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration {value!r}")
    return total


def format_duration(seconds: Optional[float]) -> str:
    if seconds is None:
        return "Never"
    if seconds == int(seconds):
        sec = int(seconds)
        if sec % 3600 == 0:
            return f"{sec // 3600}h"
        if sec % 60 == 0:
            return f"{sec // 60}m"
        return f"{sec}s"
    return f"{seconds}s"
