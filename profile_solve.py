"""Dev-only: profile the config-3 warm solve (cProfile + phase timers).

Usage: python profile_solve.py [pods] [types]
Env: BENCH_BACKEND=cpu to force the CPU fallback for comparison.
"""

import cProfile
import io
import os
import pstats
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench


def main():
    out = {}
    backend = bench.resolve_backend(out)
    print("backend:", backend, file=sys.stderr)

    from karpenter_core_tpu.apis import labels as wk
    from karpenter_core_tpu.apis.nodepool import NodePool
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_core_tpu.kube.objects import (
        LabelSelector,
        Toleration,
        TopologySpreadConstraint,
    )
    from karpenter_core_tpu.solver import TPUScheduler

    n_pods = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    n_types = int(sys.argv[2]) if len(sys.argv) > 2 else 2_000
    rng = np.random.RandomState(11)
    provider = FakeCloudProvider()
    provider.instance_types = instance_types(n_types)
    nodepool = NodePool()
    nodepool.metadata.name = "default"

    def constrained(i):
        sel = tol = spread = None
        labels = {"app": f"svc-{i % 9}"}
        r = i % 9
        if r < 3:
            sel = {wk.CAPACITY_TYPE_LABEL_KEY: ["spot", "on-demand"][i % 2]}
        elif r < 5:
            tol = [Toleration(key="dedicated", operator="Exists")]
        elif r < 7:
            spread = [TopologySpreadConstraint(
                max_skew=1, topology_key=wk.LABEL_TOPOLOGY_ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels={"app": labels["app"]}))]
        cpu = ["100m", "250m", "500m", "1", "1500m", "2"][rng.randint(6)]
        mem = ["128Mi", "256Mi", "512Mi", "1Gi", "2Gi"][rng.randint(5)]
        return bench._mk_pod(i, cpu, mem, selector=sel, tolerations=tol,
                             spread=spread, labels=labels)

    pods = [constrained(i) for i in range(n_pods)]
    solver = TPUScheduler([nodepool], provider)
    t0 = time.perf_counter()
    solver.solve(pods)
    print(f"cold: {(time.perf_counter()-t0)*1000:.1f} ms", file=sys.stderr)
    for _ in range(2):
        t0 = time.perf_counter()
        res = solver.solve(pods)
        print(f"warm: {(time.perf_counter()-t0)*1000:.1f} ms "
              f"({res.pods_scheduled} pods, {res.node_count} nodes)", file=sys.stderr)
    ms = solver.last_merge_stats or {}
    print(
        "merge: engine={} {:.1f} ms, {} records, {} screened, {} applied".format(
            ms.get("merge_engine", "-"),
            ms.get("merge_ms", 0.0),
            ms.get("merge_records", 0),
            ms.get("merge_candidates_screened", 0),
            ms.get("merge_pairs_applied", 0),
        ),
        file=sys.stderr,
    )

    pr = cProfile.Profile()
    pr.enable()
    solver.solve(pods)
    pr.disable()
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(45)
    print(s.getvalue())


if __name__ == "__main__":
    main()
