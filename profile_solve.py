"""Dev-only: profile the config-3 warm solve (cProfile + phase timers).

Usage: python profile_solve.py [pods] [types] [--ticks N] [--churn RATE]
       python profile_solve.py --stream SCENARIO [--scale N] [--pace S]
       python profile_solve.py --disrupt [--nodes N] [--pods-per-node K]
       python profile_solve.py [pods] [types] --backend {ffd,lp,auto}
       python profile_solve.py --fleet N [--tenant-pods K --engine {batched,solo}]

With --backend, the solve runs under that pack backend
(KARPENTER_TPU_PACK_BACKEND; solver/backends/) — lp additionally prints
the plan cost, the LP relaxation lower bound, and the optimality gap,
so either backend can be profiled off-TPU with BENCH_BACKEND=cpu.

With --fleet N, profiles one fleet mega-solve round over N tenants
(fleet/megasolve.py; bench.fleet_env's mixed catalog archetypes) —
burst timing, the profiled warm round, the mega-dispatch coalescing
stats, and the skeleton-plane size; --engine solo profiles the
per-tenant oracle path instead.

With --disrupt, builds the config-9 consolidation fleet (bench.py
disrupt_fleet: N nodes, N*K bound pods, 5% budget), runs one cold
batched decision, prints warm decision timings + the engine's
bounds/subset stats, then cProfile of one warm decision — the
disruption analogue of the solve profiles, through the same batched
engine the DisruptionController runs (disruption/engine.py).

With --ticks, drives N repeated solves through the steady-state
incremental path (solver/incremental.py) over a churning batch —
RATE (default 0.05) of the pods are swapped each tick — printing each
tick's host/device split and cache hit counts, then cProfile of one
steady-state warm tick. Without --ticks, the original single-solve
profile runs.

With --stream, drives a traffic-generator scenario (serving/trafficgen
.py: rollout, spot_storm, cascade, diurnal, churn10x) through the async
serving pipeline under cProfile — the same path bench config 8 and the
operator's USE_SERVING_PIPELINE mode run, so slow-solve capture
(KARPENTER_TPU_TRACE_SLOW_MS + KARPENTER_TPU_TRACE_DIR) and
/debug/traces work identically in streaming mode. Prints the run summary (decision-latency SLO,
per-stage attribution, queue stats) then the profile.

Env: BENCH_BACKEND=cpu to force the CPU fallback for comparison;
KARPENTER_TPU_INCREMENTAL=0 to profile the cold pipeline tick over tick.
"""

import argparse
import cProfile
import io
import os
import pstats
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench


def _parse_args():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("pods", nargs="?", type=int, default=50_000)
    ap.add_argument("types", nargs="?", type=int, default=2_000)
    ap.add_argument("--ticks", type=int, default=0,
                    help="steady-state mode: repeated solves with churn")
    ap.add_argument("--churn", type=float, default=0.05,
                    help="fraction of pods swapped per tick (with --ticks)")
    ap.add_argument("--stream", metavar="SCENARIO", default=None,
                    help="streaming mode: profile a trafficgen scenario "
                         "through the serving pipeline")
    ap.add_argument("--scale", type=int, default=400,
                    help="scenario base-fleet size (with --stream)")
    ap.add_argument("--pace", type=float, default=0.1,
                    help="seconds between scenario steps (with --stream)")
    ap.add_argument("--mode", default="pipeline",
                    choices=("pipeline", "sequential"),
                    help="serving mode to profile (with --stream)")
    ap.add_argument("--disrupt", action="store_true",
                    help="disruption mode: profile a batched "
                         "consolidation decision over the config-9 fleet")
    ap.add_argument("--nodes", type=int, default=500,
                    help="fleet size (with --disrupt)")
    ap.add_argument("--pods-per-node", type=int, default=100,
                    help="bound pods per node (with --disrupt)")
    ap.add_argument("--engine", default="batched",
                    choices=("batched", "sequential", "solo"),
                    help="engine to profile: batched|sequential with "
                         "--disrupt, batched|solo with --fleet")
    ap.add_argument("--backend", default=None, choices=("ffd", "lp", "auto"),
                    help="pack backend to profile (KARPENTER_TPU_PACK_BACKEND;"
                         " solver/backends/ — lp reports plan cost, the"
                         " relaxation bound, and the optimality gap)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet mode: profile a mega-solve round over N "
                         "tenants (fleet/megasolve.py; mixed catalog "
                         "archetypes from bench.fleet_env)")
    ap.add_argument("--tenant-pods", type=int, default=200,
                    help="pods per tenant per round (with --fleet)")
    ap.add_argument("--shard", type=int, default=0, metavar="N",
                    help="mega-shard mode: profile one pod-axis sharded "
                         "mega-solve over an N-way mesh (solver/sharding.py"
                         " sharded_mega_solve; off-TPU this forces N XLA "
                         "host devices before jax initializes)")
    ap.add_argument("--shard-pods", type=int, default=500_000,
                    help="pod count (with --shard)")
    ap.add_argument("--shard-types", type=int, default=10_000,
                    help="type count (with --shard)")
    ap.add_argument("--constraints", metavar="SCENARIO", default=None,
                    choices=("spread_skew", "anti_dense", "stateful_dense"),
                    help="constraint-dense mode (ISSUE 12): profile one "
                         "config-13 scenario through the tensor path "
                         "(bench.constraint_env; --engine "
                         "tensor|oracle picks the constraint engine; "
                         "BENCH_BACKEND=cpu off-TPU)")
    ap.add_argument("--constraint-pods", type=int, default=10_000,
                    help="pod count (with --constraints)")
    ap.add_argument("--restart", action="store_true",
                    help="warm-state persistence mode (ISSUE 13): build a "
                         "config-7-shaped warm world, snapshot it, simulate "
                         "a process death, then profile the snapshot -> "
                         "restore -> first-solve path (BENCH_BACKEND=cpu "
                         "off-TPU)")
    ap.add_argument("--snapshot", metavar="PATH", default=None,
                    help="with --restart: restore this existing snapshot "
                         "instead of taking a fresh one")
    ap.add_argument("--restart-pods", type=int, default=5_000,
                    help="pod count (with --restart)")
    ap.add_argument("--restart-types", type=int, default=500,
                    help="catalog size (with --restart)")
    ap.add_argument("--device", action="store_true",
                    help="device-plane mode (ISSUE 16): one cold + one "
                         "warm solve, then the compile table (fn x shape "
                         "x count x compile_ms), per-phase transfer "
                         "totals, and the HBM watermark; pass a modest "
                         "pods count (e.g. 5000 500) and BENCH_BACKEND="
                         "cpu off-TPU")
    return ap.parse_args()


def main():
    args = _parse_args()
    if args.backend:
        # mirrors --disrupt/--stream: one flag pins the engine for the
        # whole process (off-TPU: combine with BENCH_BACKEND=cpu)
        os.environ["KARPENTER_TPU_PACK_BACKEND"] = args.backend
    if args.shard:
        # the mesh width is an XLA init flag — force host devices
        # BEFORE the first jax import (resolve_backend) when no real
        # multi-device platform is pinned
        platform = os.environ.get("JAX_PLATFORMS", "")
        if os.environ.get("BENCH_BACKEND") == "cpu" or platform.startswith("cpu") or not platform:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={args.shard}"
                ).strip()
            os.environ.setdefault("BENCH_BACKEND", "cpu")
    out = {}
    backend = bench.resolve_backend(out)
    print("backend:", backend, file=sys.stderr)
    if args.shard:
        _shard_mode(args)
        return
    if args.stream:
        _stream_mode(args)
        return
    if args.disrupt:
        _disrupt_mode(args)
        return
    if args.fleet:
        _fleet_mode(args)
        return
    if args.constraints:
        _constraints_mode(args)
        return
    if args.restart:
        _restart_mode(args)
        return

    from karpenter_core_tpu.apis import labels as wk
    from karpenter_core_tpu.apis.nodepool import NodePool
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_core_tpu.kube.objects import (
        LabelSelector,
        Toleration,
        TopologySpreadConstraint,
    )
    from karpenter_core_tpu.solver import TPUScheduler

    n_pods = args.pods
    n_types = args.types
    rng = np.random.RandomState(11)
    provider = FakeCloudProvider()
    provider.instance_types = instance_types(n_types)
    nodepool = NodePool()
    nodepool.metadata.name = "default"

    def constrained(i):
        sel = tol = spread = None
        labels = {"app": f"svc-{i % 9}"}
        r = i % 9
        if r < 3:
            sel = {wk.CAPACITY_TYPE_LABEL_KEY: ["spot", "on-demand"][i % 2]}
        elif r < 5:
            tol = [Toleration(key="dedicated", operator="Exists")]
        elif r < 7:
            spread = [TopologySpreadConstraint(
                max_skew=1, topology_key=wk.LABEL_TOPOLOGY_ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels={"app": labels["app"]}))]
        cpu = ["100m", "250m", "500m", "1", "1500m", "2"][rng.randint(6)]
        mem = ["128Mi", "256Mi", "512Mi", "1Gi", "2Gi"][rng.randint(5)]
        return bench._mk_pod(i, cpu, mem, selector=sel, tolerations=tol,
                             spread=spread, labels=labels)

    pods = [constrained(i) for i in range(n_pods)]
    solver = TPUScheduler([nodepool], provider)
    if args.device:
        _device_mode(solver, pods)
        return
    if args.ticks:
        _tick_mode(args, solver, pods, constrained, rng)
        return
    t0 = time.perf_counter()
    solver.solve(pods)
    print(f"cold: {(time.perf_counter()-t0)*1000:.1f} ms", file=sys.stderr)
    # the cold solve is the one that DISPATCHES the pack backend (warm
    # repeats are jobs-memo hits), so its guard/optimality counters and
    # the LP backend's refinement trajectory live here
    ps_stats = dict(getattr(solver, "last_pack_stats", None) or {})
    for _ in range(2):
        t0 = time.perf_counter()
        res = solver.solve(pods)
        print(f"warm: {(time.perf_counter()-t0)*1000:.1f} ms "
              f"({res.pods_scheduled} pods, {res.node_count} nodes)", file=sys.stderr)
    if ps_stats.get("backend") not in (None, "ffd"):
        from karpenter_core_tpu.solver import plancost

        block = plancost.cost_block(res, provider.instance_types)
        print(
            "pack backend: {} (lp_won={} ffd_kept={} saved=${}/hr) "
            "cost=${}/hr bound=${}/hr gap={}%".format(
                ps_stats.get("backend"),
                ps_stats.get("lp_won", 0),
                ps_stats.get("ffd_kept", 0),
                round(ps_stats.get("lp_saved_per_hr", 0.0), 2),
                block["plan_cost_per_hr"],
                block["lp_bound_per_hr"],
                block["opt_gap_pct"],
            ),
            file=sys.stderr,
        )
        _print_optim_tier(ps_stats)
    ms = solver.last_merge_stats or {}
    print(
        "merge: engine={} {:.1f} ms, {} records, {} screened, {} applied".format(
            ms.get("merge_engine", "-"),
            ms.get("merge_ms", 0.0),
            ms.get("merge_records", 0),
            ms.get("merge_candidates_screened", 0),
            ms.get("merge_pairs_applied", 0),
        ),
        file=sys.stderr,
    )

    pr = cProfile.Profile()
    pr.enable()
    solver.solve(pods)
    pr.disable()
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(45)
    print(s.getvalue())


def _print_optim_tier(ps_stats: dict) -> None:
    """--backend lp: the ISSUE-19 optimality tier's work, per solve —
    the refinement trajectory (per-round certified dual bound, primal
    cost, whether the round's re-rounded candidate beat the incumbent,
    wall ms) and the restricted branch-and-bound table (which
    signature→type flips were considered, their bounds, and whether
    each was pruned, explored, or won)."""
    from karpenter_core_tpu.solver import backends as backend_mod

    try:
        b = backend_mod.get_backend(ps_stats.get("backend", "lp"))
    except Exception:  # noqa: BLE001 — reporting must not break profiling
        return
    b = getattr(b, "_lp", b)  # auto wraps a private LPBackend
    traj = getattr(b, "last_refine_trajectory", None) or []
    if traj:
        print("refinement trajectory (round 0 = cold relax+repair):",
              file=sys.stderr)
        for row in traj:
            print(
                "  round {:>2}: bound=${:<10.4f} cost=${:<10.4f} {} {:.2f} ms"
                .format(
                    row.get("round", 0),
                    row.get("bound", 0.0),
                    row.get("cost", float("nan")),
                    "improved " if row.get("improved") else "kept     ",
                    row.get("ms", 0.0),
                ),
                file=sys.stderr,
            )
    table = getattr(b, "last_branch_table", None) or []
    if table:
        print("branch table (top-k fractional signature→type flips):",
              file=sys.stderr)
        for row in table:
            print(
                "  job {:>2} sig {:>3} x{:<4} {}→{}: bound=${:<10.4f} "
                "cost={} {}".format(
                    row.get("job", 0),
                    row.get("sig", 0),
                    row.get("count", 0),
                    row.get("from_t", "?"),
                    row.get("to_t", "?"),
                    row.get("bound", 0.0),
                    ("$%.4f" % row["cost"]) if row.get("cost") is not None
                    else "-",
                    row.get("outcome", "?"),
                ),
                file=sys.stderr,
            )
    st = getattr(b, "last_stats", None) or {}
    if traj or table:
        print(
            "optimality tier: refine_rounds={} accepted={} branches "
            "considered={} pruned={} explored={} won={} ascent_iters={}"
            .format(
                st.get("refine_rounds", 0), st.get("refine_accepted", 0),
                st.get("branches_considered", 0), st.get("branches_pruned", 0),
                st.get("branches_explored", 0), st.get("branches_won", 0),
                st.get("ascent_iters", 0),
            ),
            file=sys.stderr,
        )


def _device_mode(solver, pods):
    """--device: one cold + one warm solve through the device-plane
    observatory (ISSUE 16) — compile attribution per registered jit
    entry point, H2D/D2H bytes per solve phase, and the HBM watermark
    (off-TPU the cpu backend reports no watermarks; the padded-buffer
    footprint estimate stands in)."""
    from karpenter_core_tpu.solver import devicetime
    from karpenter_core_tpu.tracing import deviceplane

    for label in ("cold", "warm"):
        t0 = time.perf_counter()
        res = solver.solve(pods)
        dev = solver.last_device_stats or {}
        print(
            f"{label}: {(time.perf_counter()-t0)*1000:.1f} ms  "
            f"compiles={dev.get('compiles', 0)}  "
            f"({res.pods_scheduled} pods, {res.node_count} nodes)",
            file=sys.stderr,
        )

    def fmt_node(node):
        # ("a", shape, dtype) → "4096x128:bool"; pytrees compact to their
        # array-leaf census; static reprs pass through truncated
        if node and node[0] == "a":
            return "x".join(str(d) for d in node[1]) + ":" + str(node[2])
        if node and node[0] in ("d", "t"):
            leaves = []

            def walk(n):
                if not isinstance(n, (list, tuple)) or not n:
                    return
                if n[0] == "a":
                    leaves.append("x".join(str(d) for d in n[1]))
                    return
                for child in n[1:]:
                    walk(child[1] if n[0] == "d" else child)

            walk(node)
            head = ",".join(leaves[:4]) + ("…" if len(leaves) > 4 else "")
            return f"pytree({len(leaves)}a:{head})"
        text = str(node)
        return text if len(text) <= 60 else text[:57] + "..."

    print("\ncompile table (fn x shape x count x compile_ms):", file=sys.stderr)
    for rec in deviceplane.registry_state():
        if not rec["signatures"]:
            continue
        print(
            f"  {rec['fn']}  [{rec['call_site']}]  calls={rec['calls']} "
            f"compiles={rec['compiles']} evicted={rec['evicted']}",
            file=sys.stderr,
        )
        for sig in rec["signatures"]:
            shapes = ", ".join(fmt_node(tuple(n)) for _, n in (tuple(s) for s in sig["shapes"]))
            static = ", ".join(str(n) for _, n in (tuple(s) for s in sig["static"]))
            tag = " (restored)" if sig["restored"] else ""
            print(
                f"    [{shapes or '-'}] static[{static or '-'}] "
                f"x{sig['count']}  first {sig['first_ms']} ms{tag}",
                file=sys.stderr,
            )

    dev = solver.last_device_stats or {}
    print("\ntransfer totals per phase (warm solve):", file=sys.stderr)
    by_phase = dev.get("transfer_by_phase", {})
    if not by_phase:
        print("  none recorded", file=sys.stderr)
    for phase, dirs in sorted(by_phase.items()):
        split = "  ".join(f"{d}={n}B" for d, n in sorted(dirs.items()))
        print(f"  {phase}: {split}", file=sys.stderr)
    print("process totals:", deviceplane.totals()["transfer_bytes"], file=sys.stderr)

    hbm = devicetime.device_memory_stats()
    if hbm:
        print(f"\nHBM watermark: {hbm}", file=sys.stderr)
    else:
        print(
            f"\nHBM watermark: n/a on this backend — padded footprint "
            f"estimate {dev.get('footprint_bytes', 0)} B of "
            f"{dev.get('tile_budget_mb')} MiB tile budget "
            f"(headroom {dev.get('tile_headroom_frac')})",
            file=sys.stderr,
        )


def _restart_mode(args):
    """--restart [--snapshot PATH]: profile the snapshot → restore →
    prewarm-replay → first-solve path (ISSUE 13 + 17). Builds a
    config-7-shaped workload, warms a solver, snapshots, wipes every
    in-memory plane exactly as a process exit would
    (warmstore.simulate_process_death), then profiles restore, the boot
    jitsig replay (solver/prewarm.py — the compile table is printed
    before and after, so the zero-compile first solve is visible), and
    the first post-restart solve against fresh pod/catalog objects —
    what a restarted provisioner actually executes. Point
    KARPENTER_TPU_COMPILE_CACHE_DIR at a persistent dir (+ _CPU_OK=1
    off-TPU) to exercise the managed executable cache too."""
    import tempfile
    import time as _time

    from karpenter_core_tpu.apis.nodepool import NodePool
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider, new_instance_type
    from karpenter_core_tpu.kube.objects import NodeSelectorRequirement
    from karpenter_core_tpu.solver import TPUScheduler, warmstore

    teams = 40
    rng = np.random.RandomState(23)
    specs = [
        (
            ["100m", "250m", "500m", "1", "2", "4"][rng.randint(6)],
            ["128Mi", "512Mi", "1Gi", "2Gi", "4Gi"][rng.randint(5)],
            "1" if rng.rand() < 0.1 else None,
            int(i % teams),
        )
        for i in range(args.restart_pods)
    ]
    cat_specs = [
        (f"cap-{i}", {"cpu": str((i % 64) + 1), "memory": f"{2 * ((i % 64) + 1)}Gi", "pods": "110"})
        for i in range(args.restart_types)
    ] + [
        (f"cap-gpu-{g}", {"cpu": str(8 * (g + 1)), "memory": f"{16 * (g + 1)}Gi",
                          "pods": "110", "nvidia.com/gpu": str(min(8, g + 1))})
        for g in range(20)
    ]

    def build_world():
        provider = FakeCloudProvider()
        provider.instance_types = [new_instance_type(n, r) for n, r in cat_specs]
        provider.bump_catalog_generation()
        np_ = NodePool()
        np_.metadata.name = "default"
        np_.spec.template.requirements = [
            NodeSelectorRequirement("bench-team", "In", [f"t{t}" for t in range(teams)])
        ]
        pods = [
            bench._mk_pod(i, cpu, mem, gpu=gpu,
                          selector={"bench-team": f"t{t}"}, labels={"bench-team": f"t{t}"})
            for i, (cpu, mem, gpu, t) in enumerate(specs)
        ]
        return provider, np_, pods

    path = args.snapshot
    if path is None:
        provider, np_, pods = build_world()
        warm = TPUScheduler([np_], provider)
        for _ in range(2):
            warm.solve(pods)
        t0 = _time.perf_counter()
        path = warm.snapshot(directory=tempfile.mkdtemp(prefix="profile-warmstore-"))
        print(f"snapshot: {path} ({(_time.perf_counter()-t0)*1000:.1f} ms)", file=sys.stderr)
    warmstore.simulate_process_death()
    # fresh objects: a restarted process re-reads pods/catalog from the
    # apiserver/provider — nothing may carry the dead process's memos
    provider, np_, pods = build_world()
    solver = TPUScheduler([np_], provider)

    from karpenter_core_tpu.solver import backend as solver_backend
    from karpenter_core_tpu.solver import prewarm
    from karpenter_core_tpu.tracing import deviceplane

    def compile_table(header):
        # fn x restored-signature count x live compile count — the
        # before/after view of the boot jitsig replay (ISSUE 17)
        print(f"\n{header}:", file=sys.stderr)
        rows = [r for r in deviceplane.registry_state() if r["signatures"]]
        if not rows:
            print("  (no registered jit entry points)", file=sys.stderr)
        for rec in rows:
            restored = sum(1 for s in rec["signatures"] if s["restored"])
            print(
                f"  {rec['fn']}  sigs={len(rec['signatures'])} "
                f"restored={restored} compiles={rec['compiles']}",
                file=sys.stderr,
            )
        t = deviceplane.totals()
        print(
            f"  totals: compiles={t['compiles']} "
            f"prewarm_compiles={t['prewarm_compiles']}",
            file=sys.stderr,
        )

    pr = cProfile.Profile()
    pr.enable()
    t0 = _time.perf_counter()
    outcome = solver.restore(path)
    restore_ms = (_time.perf_counter() - t0) * 1000.0
    pr.disable()
    print(
        f"restore: {restore_ms:.1f} ms  restored={outcome.get('restored')} "
        f"dropped={outcome.get('dropped')}",
        file=sys.stderr,
    )
    print(
        f"compile cache: {solver_backend.compile_cache_status()}",
        file=sys.stderr,
    )
    compile_table("compile table before prewarm (restored rows, no live code)")
    pr.enable()
    replay = prewarm.warmup_compile_only(solver)
    pr.disable()
    print(f"\nprewarm replay: {replay}", file=sys.stderr)
    compile_table("compile table after prewarm (replayed under prewarm_replay)")
    pr.enable()
    t0 = _time.perf_counter()
    res = solver.solve(pods)
    first_ms = (_time.perf_counter() - t0) * 1000.0
    pr.disable()
    dev = solver.last_device_stats or {}
    print(
        f"\nfirst solve after restore: {first_ms:.1f} ms "
        f"(host {solver.last_timings['host_ms']:.1f} ms, "
        f"{res.pods_scheduled} pods, {res.node_count} nodes) "
        f"compile_events={dev.get('compiles', 0)} "
        f"cache={solver.last_cache_stats}",
        file=sys.stderr,
    )
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(40)
    print(s.getvalue())


def _constraints_mode(args):
    """--constraints SCENARIO: profile one config-13 constraint-dense
    solve (ISSUE 12) through the chosen constraint engine — the route
    split and stateful/exclusion mask costs show up as pack.*, merge
    and existing_pack.stateful phases (tensor) or oracle_fallback
    (engine=oracle, the legacy path)."""
    import time as _time

    from karpenter_core_tpu.solver import TPUScheduler, incremental

    engine = args.engine if args.engine in ("tensor", "oracle") else "tensor"
    os.environ["KARPENTER_TPU_CONSTRAINT_ENGINE"] = engine
    pods, provider, nodepool, kube, nodes_factory = bench.constraint_env(
        args.constraints, args.constraint_pods
    )
    print(
        f"constraints: scenario={args.constraints} pods={len(pods)} "
        f"engine={engine}",
        file=sys.stderr,
    )
    # cold solve outside the profile (compile + catalog encode)
    incremental.reset()
    solver = TPUScheduler([nodepool], provider, kube_client=kube)
    t0 = _time.perf_counter()
    res = solver.solve(list(pods), state_nodes=nodes_factory())
    cold_ms = (_time.perf_counter() - t0) * 1000.0
    print(
        f"cold: {cold_ms:.1f} ms  nodes={res.node_count} "
        f"errors={len(res.pod_errors)} route={solver.last_route_stats}"
    )
    incremental.reset()
    solver = TPUScheduler([nodepool], provider, kube_client=kube)
    pr = cProfile.Profile()
    pr.enable()
    t0 = _time.perf_counter()
    res = solver.solve(list(pods), state_nodes=nodes_factory())
    wall_ms = (_time.perf_counter() - t0) * 1000.0
    pr.disable()
    print(
        f"profiled: {wall_ms:.1f} ms  nodes={res.node_count} "
        f"oracle_share={solver.last_route_stats.get('oracle_share')}"
    )
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(40)
    print(s.getvalue())


def _fleet_mode(args):
    """--fleet N: profile one mega-solve round over N tenants — the
    fleet path bench config 11 measures (fleet/megasolve.py; mixed
    catalog archetypes, content twins, admission-time prewarm). Warm
    round first (canonical entries + compile), then cProfile of a fresh
    round. Worker threads opt into the shared profiler like --stream's
    stage threads; off-TPU use BENCH_BACKEND=cpu."""
    import threading

    engine_name = "solo" if args.engine == "solo" else "batched"
    os.environ["KARPENTER_TPU_FLEET_ENGINE"] = engine_name
    registry, engine, tenants = bench.fleet_env(args.fleet)
    t0 = time.perf_counter()
    engine.solve_round(bench.fleet_work(tenants, args.tenant_pods, 0))
    print(
        f"burst round ({args.fleet} tenants x {args.tenant_pods} pods, "
        f"{engine_name}): {(time.perf_counter()-t0)*1000:.1f} ms",
        file=sys.stderr,
    )
    work = bench.fleet_work(tenants, args.tenant_pods, 1)
    pr = cProfile.Profile()

    def _enable_for_worker_threads(*_a):
        # each fleet worker thread turns the shared profiler on for
        # itself at its first call event (the --stream pattern); XLA
        # pool threads stay unprofiled
        if threading.current_thread().name.startswith("fleet-worker-"):
            threading.setprofile(None)
            pr.enable()

    threading.setprofile(_enable_for_worker_threads)
    pr.enable()
    t0 = time.perf_counter()
    outcomes = engine.solve_round(work)
    dt = time.perf_counter() - t0
    pr.disable()
    threading.setprofile(None)
    errors = {t: o.error for t, o in outcomes.items() if o.error}
    print(
        f"profiled round: {dt*1000:.1f} ms, "
        f"{sum(o.pods for o in outcomes.values())} pods, errors={errors or 'none'}",
        file=sys.stderr,
    )
    print(f"dispatch: {engine.last_round.get('dispatch')}", file=sys.stderr)
    print(f"skeleton plane: {len(engine.skeletons)} entries", file=sys.stderr)
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(45)
    print(s.getvalue())


def _shard_mode(args):
    """--shard N: one pod-axis sharded mega-solve (ISSUE 11) over an
    N-way mesh at --shard-pods × --shard-types, cold + warm timings with
    the per-stage split and padding stats, a sharded-vs-unsharded engine
    identity check at a subsampled shape, then cProfile of one warm
    sharded solve. Off-TPU this is the config-12 cell, in-process."""
    import shardbench

    from karpenter_core_tpu.solver.sharding import make_mesh, sharded_mega_solve

    mesh = make_mesh(args.shard)
    alloc, prices = shardbench.build_catalog(args.shard_types, 4, 12)
    reqs = shardbench.build_pods(args.shard_pods, 4, 13)
    sig_masks, type_masks = shardbench.build_masks(8, args.shard_types, 14)
    t0 = time.perf_counter()
    res = sharded_mega_solve(mesh, reqs, alloc, prices, sig_masks, type_masks)
    print(f"cold: {(time.perf_counter()-t0)*1000:.1f} ms", file=sys.stderr)
    for i in range(2):
        res = sharded_mega_solve(mesh, reqs, alloc, prices, sig_masks, type_masks)
        print(
            f"warm {i}: {res['wall_ms']:.1f} ms (compat {res['compat_ms']}, "
            f"pack {res['pack_ms']}, assign {res['assign_ms']}) "
            f"{res['scheduled']} pods, {res['nodes']} nodes, "
            f"frontier {res['frontier_rows']} rows",
            file=sys.stderr,
        )
    print(f"shard stats: {res['shard']}", file=sys.stderr)
    sub = shardbench.run_parity(mesh, min(args.shard_pods, 20_000), args.shard_types, 1)
    print(
        f"engine parity at {sub['pods']} pods: "
        f"{sub['identical']}/{sub['cells']} identical",
        file=sys.stderr,
    )
    pr = cProfile.Profile()
    pr.enable()
    sharded_mega_solve(mesh, reqs, alloc, prices, sig_masks, type_masks)
    pr.disable()
    s = io.StringIO()
    pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(45)
    print(s.getvalue())


def _stream_mode(args):
    """--stream SCENARIO: one traffic measurement through the serving
    pipeline under cProfile. The profile covers every stage thread
    (cProfile hooks threads started after enable()), so prewarm and
    window-former costs show up next to the authoritative solve."""
    import json
    import threading

    from karpenter_core_tpu.serving import trafficgen as tg

    pr = cProfile.Profile()

    def _enable_for_stage_threads(*_a):
        # each serving stage thread turns the shared profiler on for
        # itself at its first call event (the GIL serializes the
        # callbacks; dev-only). Foreign pool threads (XLA, informers)
        # stay unprofiled — profiling them crawls the whole process.
        name = threading.current_thread().name
        if name.startswith(("serve-", "seq-")):
            threading.setprofile(None)
            pr.enable()

    threading.setprofile(_enable_for_stage_threads)
    pr.enable()
    summary = tg.run_measurement(
        args.stream, args.mode, "free", args.scale, args.pace
    )
    pr.disable()
    threading.setprofile(None)
    print(json.dumps(summary, indent=1), file=sys.stderr)
    s = io.StringIO()
    pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(45)
    print(s.getvalue())


def _disrupt_mode(args):
    """--disrupt: cold + warm batched consolidation decisions over the
    config-9 fleet, engine stats, then cProfile of one warm decision
    (bounds memo hot: the profile shows the verification solve and the
    decision's host overhead, not the one-time family screen)."""
    import json

    env, scenario, bind_step, _mutate = bench.disrupt_fleet(
        args.nodes, args.pods_per_node
    )
    try:
        base = bind_step(scenario.steps[0])
        env.now += 3600.0
        print(f"fleet: {args.nodes} nodes, {base['bound']} bound pods "
              f"({base['dropped']} dropped)", file=sys.stderr)
        _, cold_ms, stats, n_cands = bench.disrupt_decide(env, args.engine)
        print(f"cold decision: {cold_ms:.1f} ms over {n_cands} candidates",
              file=sys.stderr)
        for i in range(3):
            _, dt, stats, _ = bench.disrupt_decide(env, args.engine)
            print(f"warm decision {i}: {dt:.1f} ms", file=sys.stderr)
        print("engine stats:", json.dumps(stats, indent=1, default=str),
              file=sys.stderr)
        pr = cProfile.Profile()
        pr.enable()
        bench.disrupt_decide(env, args.engine)
        pr.disable()
        s = io.StringIO()
        pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(45)
        print(s.getvalue())
    finally:
        env.stop()


def _tick_mode(args, solver, pods, make_pod, rng):
    """--ticks N --churn RATE: repeated solves through the incremental
    path, per-tick host/device + cache traffic, then cProfile of one
    steady-state warm tick."""
    next_id = [len(pods)]

    def churn():
        n = max(1, int(len(pods) * args.churn))
        drop = set(rng.choice(len(pods), n, replace=False).tolist())
        pods[:] = [p for i, p in enumerate(pods) if i not in drop]
        for _ in range(n):
            pods.append(make_pod(next_id[0]))
            next_id[0] += 1

    for tick in range(args.ticks):
        if tick:
            churn()
        t0 = time.perf_counter()
        res = solver.solve(pods)
        dt = (time.perf_counter() - t0) * 1000.0
        t = solver.last_timings or {}
        cs = solver.last_cache_stats or {}
        print(
            f"tick {tick}: {dt:.1f} ms (host {t.get('host_ms', 0):.1f}, "
            f"device {t.get('device_ms', 0):.1f}) "
            f"{res.pods_scheduled} pods, {res.node_count} nodes, "
            f"cache hit_rate={cs.get('hit_rate', 0)} hits={cs.get('hits', {})} "
            f"misses={cs.get('misses', {})}",
            file=sys.stderr,
        )
    churn()
    pr = cProfile.Profile()
    pr.enable()
    solver.solve(pods)
    pr.disable()
    s = io.StringIO()
    pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(45)
    print(s.getvalue())


if __name__ == "__main__":
    main()
