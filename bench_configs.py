"""The five BASELINE.json evaluation configs, one JSON line each.

`bench.py` stays the driver's single-line headline (the 50k x 2k north
star); this script covers the full evaluation grid:

  1. 1k uniform CPU-only pods, 10 types, single NodePool — CPU ref path
  2. 10k mixed cpu/mem/gpu pods, 500 types — resource-fit only
  3. 50k pods with nodeSelector + taints + topology spread (+ parity)
  4. Multi-node consolidation: 5k underutilized nodes → repack screen
  5. Spot-price-weighted packing: 2k types x 6 zones, cost objective

Run: python bench_configs.py [1 2 3 4 5]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _pods_line(name, n_pods, elapsed, extra=None):
    out = {
        "metric": name,
        "value": round(n_pods / elapsed, 1) if elapsed > 0 else 0.0,
        "unit": "pods/sec",
        "vs_baseline": round(n_pods / elapsed / 100.0, 2) if elapsed > 0 else 0.0,
    }
    if extra:
        out.update(extra)
    print(json.dumps(out), flush=True)


def _setup():
    import os

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _mk_pod(i, cpu, mem, gpu=None, selector=None, tolerations=None, spread=None, labels=None):
    from karpenter_core_tpu.kube.objects import (
        Container,
        Pod,
        PodCondition,
        PodSpec,
        ResourceRequirements,
    )
    from karpenter_core_tpu.kube.quantity import parse_quantity

    pod = Pod()
    pod.metadata.name = f"bench-{i}"
    pod.metadata.labels = dict(labels or {})
    requests = {"cpu": parse_quantity(cpu), "memory": parse_quantity(mem)}
    if gpu:
        requests["nvidia.com/gpu"] = parse_quantity(gpu)
    pod.spec = PodSpec(
        containers=[Container(name="main", resources=ResourceRequirements(requests=requests))]
    )
    if selector:
        pod.spec.node_selector = selector
    if tolerations:
        pod.spec.tolerations = tolerations
    if spread:
        pod.spec.topology_spread_constraints = spread
    pod.status.conditions = [
        PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
    ]
    return pod


def config1() -> None:
    """CPU reference (oracle) path: 1k uniform pods, 10 types."""
    from karpenter_core_tpu.apis.nodepool import NodePool
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_core_tpu.scheduler.builder import build_scheduler

    provider = FakeCloudProvider()
    provider.instance_types = instance_types(10)
    nodepool = NodePool()
    nodepool.metadata.name = "default"
    pods = [_mk_pod(i, "500m", "512Mi") for i in range(1000)]

    sched = build_scheduler(None, None, [nodepool], provider, pods)
    sched.solve(pods)  # warm (caches pod requirement extraction paths)
    sched = build_scheduler(None, None, [nodepool], provider, pods)
    t0 = time.perf_counter()
    res = sched.solve(pods)
    dt = time.perf_counter() - t0
    n = sum(len(c.pods) for c in res.new_node_claims)
    _pods_line("config1: 1k uniform pods x 10 types (CPU oracle path)", n, dt,
               {"nodes": len(res.new_node_claims)})


def config2() -> None:
    """10k mixed cpu/mem/gpu pods, 500 types, resource-fit only."""
    from karpenter_core_tpu.apis.nodepool import NodePool
    from karpenter_core_tpu.cloudprovider.fake import (
        FakeCloudProvider,
        instance_types,
        new_instance_type,
    )
    from karpenter_core_tpu.solver import TPUScheduler

    rng = np.random.RandomState(7)
    provider = FakeCloudProvider()
    cat = instance_types(480)
    for g in range(20):  # gpu-bearing types
        cat.append(
            new_instance_type(
                f"fake-gpu-{g}",
                {"cpu": str(8 * (g + 1)), "memory": f"{16 * (g + 1)}Gi",
                 "pods": "110", "nvidia.com/gpu": str(min(8, g + 1))},
            )
        )
    provider.instance_types = cat
    nodepool = NodePool()
    nodepool.metadata.name = "default"

    pods = []
    for i in range(10_000):
        cpu = ["100m", "250m", "500m", "1", "2", "4"][rng.randint(6)]
        mem = ["128Mi", "512Mi", "1Gi", "2Gi", "4Gi"][rng.randint(5)]
        gpu = "1" if rng.rand() < 0.1 else None
        pods.append(_mk_pod(i, cpu, mem, gpu=gpu))

    solver = TPUScheduler([nodepool], provider)
    solver.solve(pods)
    t0 = time.perf_counter()
    res = solver.solve(pods)
    dt = time.perf_counter() - t0
    _pods_line("config2: 10k mixed cpu/mem/gpu pods x 500 types (TPU)",
               res.pods_scheduled, dt, {"nodes": res.node_count})


def config3() -> None:
    """50k constrained pods (nodeSelector + tolerations + spread) + parity."""
    from karpenter_core_tpu.apis import labels as wk
    from karpenter_core_tpu.apis.nodepool import NodePool
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_core_tpu.kube.objects import (
        LabelSelector,
        Toleration,
        TopologySpreadConstraint,
    )
    from karpenter_core_tpu.scheduler.builder import build_scheduler
    from karpenter_core_tpu.solver import TPUScheduler

    rng = np.random.RandomState(11)
    provider = FakeCloudProvider()
    provider.instance_types = instance_types(2000)
    nodepool = NodePool()
    nodepool.metadata.name = "default"

    def constrained(i):
        sel = tol = spread = None
        labels = {"app": f"svc-{i % 9}"}
        r = i % 9
        if r < 3:
            sel = {wk.CAPACITY_TYPE_LABEL_KEY: ["spot", "on-demand"][i % 2]}
        elif r < 5:
            tol = [Toleration(key="dedicated", operator="Exists")]
        elif r < 7:
            spread = [TopologySpreadConstraint(
                max_skew=1, topology_key=wk.LABEL_TOPOLOGY_ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels={"app": labels["app"]}))]
        cpu = ["100m", "250m", "500m", "1", "1500m", "2"][rng.randint(6)]
        mem = ["128Mi", "256Mi", "512Mi", "1Gi", "2Gi"][rng.randint(5)]
        return _mk_pod(i, cpu, mem, selector=sel, tolerations=tol, spread=spread, labels=labels)

    pods = [constrained(i) for i in range(50_000)]
    solver = TPUScheduler([nodepool], provider)
    solver.solve(pods)
    t0 = time.perf_counter()
    res = solver.solve(pods)
    dt = time.perf_counter() - t0

    # packing parity vs the oracle on a 5k subsample (oracle is O(P·N))
    sub = pods[:5000]
    oracle = build_scheduler(None, None, [nodepool], provider, sub).solve(sub)
    tpu_sub = TPUScheduler([nodepool], provider).solve(sub)
    o_nodes = len(oracle.new_node_claims)
    parity = 1.0 - abs(tpu_sub.node_count - o_nodes) / max(o_nodes, 1)
    _pods_line("config3: 50k constrained pods x 2k types (TPU)",
               res.pods_scheduled, dt,
               {"nodes": res.node_count, "packing_parity_vs_oracle": round(parity, 4)})


def config4() -> None:
    """Multi-node consolidation over 5k underutilized nodes.

    The reference caps candidates at 100 and binary-searches prefixes
    with a full simulation per probe (multinodeconsolidation.go:34,
    58-59, 1 min budget); the TPU screen evaluates every prefix of all
    5k candidates in one dispatch, then one oracle simulation verifies
    the chosen prefix."""
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    from test_disruption import Env

    from karpenter_core_tpu.disruption.helpers import get_candidates
    from karpenter_core_tpu.disruption.methods import MultiNodeConsolidation
    from karpenter_core_tpu.kube.objects import (
        Container,
        Pod,
        PodSpec,
        ResourceRequirements,
    )
    from karpenter_core_tpu.kube.quantity import parse_quantity

    env = Env()
    try:
        n_nodes = 5000
        for i in range(n_nodes):
            pod = Pod()
            pod.metadata.name = f"r-{i}"
            pod.spec = PodSpec(containers=[Container(
                name="c", resources=ResourceRequirements(
                    requests={"cpu": parse_quantity("100m"),
                              "memory": parse_quantity("128Mi")}))])
            env.make_initialized_node(instance_type_name="fake-it-4", pods=[pod])
        env.now += 3600.0
        assert env.cluster.synced()
        method = MultiNodeConsolidation(env.controller.ctx)
        t0 = time.perf_counter()
        candidates = get_candidates(
            env.cluster,
            env.kube,
            env.recorder,
            env.clock,
            env.provider,
            method.should_disrupt,
        )
        cmd = method.compute_command(candidates)
        dt = time.perf_counter() - t0
        print(json.dumps({
            "metric": "config4: multi-node consolidation screen, 5k underutilized nodes",
            "value": round(len(candidates) / dt, 1) if dt > 0 else 0.0,
            "unit": "candidates/sec",
            "vs_baseline": round((len(candidates) / dt) / (100 / 60.0), 2) if dt > 0 else 0.0,
            "candidates": len(candidates),
            "disrupted": len(cmd.candidates) if cmd else 0,
            "elapsed_sec": round(dt, 3),
        }), flush=True)
    finally:
        env.stop()


def config5() -> None:
    """Spot-price-weighted packing: 2k types x 6 zones, cost objective."""
    from karpenter_core_tpu.apis import labels as wk
    from karpenter_core_tpu.apis.nodepool import NodePool
    from karpenter_core_tpu.cloudprovider.fake import (
        FakeCloudProvider,
        new_instance_type,
        price_from_resources,
    )
    from karpenter_core_tpu.cloudprovider.types import Offering
    from karpenter_core_tpu.kube.quantity import parse_quantity
    from karpenter_core_tpu.solver import TPUScheduler

    rng = np.random.RandomState(3)
    zones = [f"test-zone-{z}" for z in range(1, 7)]
    cat = []
    for i in range(2000):
        cpu, mem = (i % 64) + 1, 2 * ((i % 64) + 1)
        res = {"cpu": str(cpu), "memory": f"{mem}Gi", "pods": str(max(110, cpu * 8))}
        base = price_from_resources({k: parse_quantity(v) for k, v in res.items()})
        offerings = []
        for z in zones:
            od = base * (1.0 + 0.05 * rng.rand())
            spot = od * (0.25 + 0.5 * rng.rand())  # spot discount varies by zone
            offerings.append(Offering(wk.CAPACITY_TYPE_ON_DEMAND, z, od))
            offerings.append(Offering(wk.CAPACITY_TYPE_SPOT, z, spot))
        cat.append(new_instance_type(f"fake-it-{i}", res, offerings=offerings))
    provider = FakeCloudProvider()
    provider.instance_types = cat
    nodepool = NodePool()
    nodepool.metadata.name = "default"

    pods = []
    for i in range(10_000):
        cpu = ["250m", "500m", "1", "2"][rng.randint(4)]
        mem = ["512Mi", "1Gi", "2Gi"][rng.randint(3)]
        pods.append(_mk_pod(i, cpu, mem))

    solver = TPUScheduler([nodepool], provider)
    solver.solve(pods)
    t0 = time.perf_counter()
    res = solver.solve(pods)
    dt = time.perf_counter() - t0
    spot_nodes = sum(1 for p in res.node_plans if p.capacity_type == wk.CAPACITY_TYPE_SPOT)
    _pods_line("config5: spot-weighted packing, 2k types x 6 zones (TPU)",
               res.pods_scheduled, dt,
               {"nodes": res.node_count,
                "total_price_per_hr": round(res.total_price, 2),
                "spot_node_fraction": round(spot_nodes / max(res.node_count, 1), 3)})


def main() -> None:
    _setup()
    which = [int(a) for a in sys.argv[1:]] or [1, 2, 3, 4, 5]
    for c in which:
        {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}[c]()


if __name__ == "__main__":
    main()
