"""Pod-axis sharded mega-solve scaling bench (bench config 12, ISSUE 11).

Drives ``solver.sharding.sharded_mega_solve`` — the giant-single-tenant
scale path (one 500k–1M-pod × 10k-type solve chunked across the device
mesh) — and prints ONE JSON line:

  curve          — (pods × types × n_devices) cells: median warm wall,
                   per-stage splits, nodes, pods/sec, shard padding
  parity         — sharded vs unsharded engine plan identity at
                   subsampled shapes (the unsharded vmap twin is the
                   parity oracle), plus the chunk-overhead diagnostic
                   vs the unchunked single-scan pack
  plan_identical_all / mega_*_ms / speedup_8dev_vs_1dev — flat gate
                   columns for hack/bench_ledger.py

One measurement per process (the config-8 discipline). Off-TPU the
process forces XLA host devices BEFORE importing jax
(``XLA_FLAGS=--xla_force_host_platform_device_count=N`` — the ISSUE 11
"runnable off-TPU" contract); on a machine whose resolved platform
already exposes enough devices it uses them as-is.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _scale(n: int) -> int:
    return max(1, int(n * float(os.environ.get("BENCH_SCALE", "1"))))


def build_catalog(n_types: int, n_res: int, seed: int):
    """Family-structured synthetic menu: ``n_types`` types drawn from 40
    proportionally-scaled families (real menus are dominated chains —
    the Pareto frontier stays small while the type axis is huge), plus
    size-correlated prices with ±20% jitter."""
    import numpy as np

    rng = np.random.RandomState(seed)
    fam = rng.randint(0, 40, n_types)
    base = rng.randint(4, 64, (40, n_res))
    size = (1 + rng.randint(0, 250, n_types))[:, None]
    alloc = (base[fam] * size).clip(1, 2**20).astype(np.int32)
    prices = np.round(
        (alloc.sum(axis=1, dtype=np.int64) / 100.0) * (0.8 + 0.4 * rng.rand(n_types)), 4
    )
    return alloc, prices


def build_pods(n_pods: int, n_res: int, seed: int):
    import numpy as np

    rng = np.random.RandomState(seed)
    return rng.randint(1, 300, (n_pods, n_res)).astype(np.int32)


def build_masks(n_sigs: int, n_types: int, seed: int, width: int = 64):
    import numpy as np

    rng = np.random.RandomState(seed)
    sig = (rng.rand(n_sigs, width) < 0.7).astype(np.float32)
    typ = (rng.rand(n_types, width) < 0.7).astype(np.float32)
    return sig, typ


def run_cell(mesh, pods: int, types: int, reps: int, seed: int = 12) -> dict:
    import numpy as np

    from karpenter_core_tpu.solver.sharding import sharded_mega_solve

    alloc, prices = build_catalog(types, 4, seed)
    reqs = build_pods(pods, 4, seed + 1)
    sig_masks, type_masks = build_masks(8, types, seed + 2)
    sharded_mega_solve(mesh, reqs, alloc, prices, sig_masks, type_masks)  # warm/compile
    walls, last = [], None
    for _ in range(reps):
        last = sharded_mega_solve(mesh, reqs, alloc, prices, sig_masks, type_masks)
        walls.append(last["wall_ms"])
    wall = sorted(walls)[len(walls) // 2]
    return {
        "pods": pods,
        "types": types,
        "n_devices": int(mesh.devices.size),
        "wall_ms": wall,
        "compat_ms": last["compat_ms"],
        "pack_ms": last["pack_ms"],
        "assign_ms": last["assign_ms"],
        "nodes": last["nodes"],
        "scheduled": last["scheduled"],
        "frontier_rows": last["frontier_rows"],
        "pods_per_sec": round(pods / (wall / 1000.0), 1) if wall else 0.0,
        "shard": last["shard"],
    }


def run_parity(mesh, pods: int, types: int, seeds: int) -> dict:
    """Sharded vs unsharded engine identity at a subsampled shape, plus
    the chunk-overhead diagnostic against the unchunked single scan."""
    import numpy as np

    from karpenter_core_tpu.solver.pack import ffd_pack, pareto_frontier
    from karpenter_core_tpu.solver.sharding import sharded_mega_solve

    cells = identical = 0
    ratios = []
    for seed in range(seeds):
        alloc, prices = build_catalog(types, 4, 100 + seed)
        reqs = build_pods(pods, 4, 200 + seed)
        sig_masks, type_masks = build_masks(8, types, 300 + seed)
        a = sharded_mega_solve(
            mesh, reqs, alloc, prices, sig_masks, type_masks, engine="sharded"
        )
        b = sharded_mega_solve(
            mesh, reqs, alloc, prices, sig_masks, type_masks, engine="unsharded"
        )
        cells += 1
        identical += int(
            np.array_equal(a["node_ids"], b["node_ids"])
            and np.array_equal(a["chosen_types"], b["chosen_types"])
            and abs(a["total_price"] - b["total_price"]) < 1e-9
        )
        # chunk overhead vs the unchunked scan (diagnostic, not a gate:
        # the solve path re-merges chunk tails downstream)
        order = np.lexsort((-reqs[:, 1], -reqs[:, 0]))
        frontier = pareto_frontier(alloc.astype(np.int32))
        _, n_ref = ffd_pack(reqs[order], frontier, np.int32(2**31 - 1))
        if a["nodes"]:
            ratios.append(int(n_ref) / a["nodes"])
    return {
        "pods": pods,
        "types": types,
        "cells": cells,
        "identical": identical,
        "plan_parity": 1.0 if identical == cells else round(identical / max(cells, 1), 4),
        "unchunked_node_ratio_min": round(min(ratios), 4) if ratios else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8, help="mesh width to bench up to")
    ap.add_argument("--pods", default="125000,250000,500000,1000000")
    ap.add_argument("--types", default="2000,10000")
    ap.add_argument("--mesh", default="1,2,4,8")
    ap.add_argument("--parity-pods", type=int, default=20000)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument(
        "--force-host",
        choices=("auto", "1", "0"),
        default="auto",
        help="force N XLA host devices (auto: only when no real multi-device platform is pinned)",
    )
    ap.add_argument("--json", action="store_true", help="print one JSON line")
    args = ap.parse_args(argv)

    # device resolution BEFORE the first jax import: forcing host
    # devices is an XLA init flag, not a runtime switch
    platform = os.environ.get("JAX_PLATFORMS", "")
    force = args.force_host == "1" or (
        args.force_host == "auto"
        and (
            os.environ.get("BENCH_BACKEND") == "cpu"
            or platform.startswith("cpu")
            or not platform
        )
    )
    if force:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={args.devices}"
            ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.setdefault("KARPENTER_TPU_BACKEND", "cpu")

    import jax

    from karpenter_core_tpu.solver.sharding import make_mesh, shard_map_available

    n_avail = len(jax.devices())
    out: dict = {
        "backend": jax.default_backend(),
        "forced_host_devices": args.devices if force else 0,
        "n_devices": n_avail,
        "shard_map_available": shard_map_available(),
    }
    if not shard_map_available():
        out["error"] = "no shard_map in this jax build"
        print(json.dumps(out), flush=True)
        return 1

    pods_list = [_scale(int(p)) for p in args.pods.split(",") if p]
    types_list = [int(t) for t in args.types.split(",") if t]
    mesh_list = [d for d in (int(m) for m in args.mesh.split(",") if m) if d <= n_avail]
    pods_hi, types_hi = max(pods_list), max(types_list)
    anchor_pods = _scale(500_000)
    if anchor_pods not in pods_list:
        anchor_pods = pods_hi

    # curve cells: device sweep at the anchor shape, pod sweep and type
    # sweep at the widest mesh — a cross of the three axes, deduped
    cells = []
    seen = set()
    widest = max(mesh_list)
    for d in mesh_list:
        cells.append((anchor_pods, types_hi, d))
    for p in pods_list:
        cells.append((p, types_hi, widest))
    for t in types_list:
        cells.append((anchor_pods, t, widest))
    t_start = time.perf_counter()
    curve = []
    for p, t, d in cells:
        if (p, t, d) in seen:
            continue
        seen.add((p, t, d))
        curve.append(run_cell(make_mesh(d), p, t, args.reps))
    out["curve"] = curve
    out["curve_wall_s"] = round(time.perf_counter() - t_start, 1)

    parity = run_parity(
        make_mesh(widest), _scale(args.parity_pods), types_hi, args.seeds
    )
    out["parity"] = parity
    out["plan_identical_all"] = parity["identical"] == parity["cells"]
    out["plan_parity"] = parity["plan_parity"]

    # flat gate columns for the ledger
    def cell(p, t, d):
        for c in curve:
            if c["pods"] == p and c["types"] == t and c["n_devices"] == d:
                return c
        return None

    anchor = cell(anchor_pods, types_hi, widest)
    if anchor:
        out["mega_500k_10k_ms"] = anchor["wall_ms"]
        out["mega_pods_per_sec"] = anchor["pods_per_sec"]
        out["shard_padding_waste_pods"] = anchor["shard"].get("pods_waste")
        out["shard_padding_waste_types"] = anchor["shard"].get("types_waste")
    one = cell(anchor_pods, types_hi, 1)
    if anchor and one and anchor["wall_ms"]:
        out["speedup_widest_vs_1dev"] = round(one["wall_ms"] / anchor["wall_ms"], 2)
    biggest = cell(pods_hi, types_hi, widest)
    if biggest:
        out["mega_biggest_ms"] = biggest["wall_ms"]
        out["mega_biggest_pods"] = biggest["pods"]

    print(json.dumps(out) if args.json else json.dumps(out, indent=1), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
