"""Benchmark: batched TPU scheduling throughput vs the reference's
enforced floor.

Config mirrors the reference's profiling grid (BASELINE.md: 400 instance
types, scheduling_benchmark_test.go:57-77) at 10k pods with the same
5/7 generic + 2/7 topology-constrained pod mix, solved by the TPU path
(constraint kernels + FFD scan). Baseline = the reference's test-enforced
100 pods/sec floor (scheduling_benchmark_test.go:51,177-181).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"backend"} — backend records the platform the solve actually ran on so a
CPU fallback is never mistaken for a TPU number.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    # import inside main so the JSON line is the only stdout on success
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    # resolve the JAX backend up front via the solver's hardened policy
    # (out-of-process probe with timeout + CPU fallback, one home in
    # solver.backend); BENCH_* env vars map onto the KARPENTER_TPU_* ones
    from karpenter_core_tpu.solver import backend as backend_mod

    if os.environ.get("BENCH_BACKEND"):
        os.environ["KARPENTER_TPU_BACKEND"] = os.environ["BENCH_BACKEND"]
    if os.environ.get("BENCH_PROBE_TIMEOUT"):
        os.environ["KARPENTER_TPU_PROBE_TIMEOUT"] = os.environ["BENCH_PROBE_TIMEOUT"]
    backend = backend_mod.default_backend()

    from karpenter_core_tpu.apis import labels as wk
    from karpenter_core_tpu.apis.nodepool import NodePool
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_core_tpu.kube.objects import (
        Container,
        LabelSelector,
        Pod,
        PodCondition,
        PodSpec,
        ResourceRequirements,
        TopologySpreadConstraint,
    )
    from karpenter_core_tpu.kube.quantity import parse_quantity
    from karpenter_core_tpu.solver import TPUScheduler

    # default grid = the BASELINE.json north-star config (50k × 2k)
    N_PODS = int(os.environ.get("BENCH_PODS", "50000"))
    N_TYPES = int(os.environ.get("BENCH_TYPES", "2000"))
    rng = np.random.RandomState(42)

    def make_pod(i: int, topo: bool) -> Pod:
        pod = Pod()
        pod.metadata.name = f"bench-{i}"
        pod.metadata.labels = {"app": f"bench-{i % 7}"}
        cpu = ["100m", "250m", "500m", "1", "1500m", "2"][rng.randint(6)]
        mem = ["128Mi", "256Mi", "512Mi", "1Gi", "2Gi"][rng.randint(5)]
        pod.spec = PodSpec(
            containers=[
                Container(
                    name="main",
                    resources=ResourceRequirements(
                        requests={"cpu": parse_quantity(cpu), "memory": parse_quantity(mem)}
                    ),
                )
            ]
        )
        if topo:
            # 2/7 of pods carry zone+hostname spreads like the reference mix
            pod.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=wk.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(match_labels={"app": pod.metadata.labels["app"]}),
                ),
            ]
        pod.status.conditions = [
            PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
        ]
        return pod

    pods = [make_pod(i, topo=(i % 7) >= 5) for i in range(N_PODS)]
    provider = FakeCloudProvider()
    provider.instance_types = instance_types(N_TYPES)
    nodepool = NodePool()
    nodepool.metadata.name = "default"

    solver = TPUScheduler([nodepool], provider)

    # warm-up on the full batch so every pad bucket's ffd_pack shape is
    # compiled before the timed run (jit caches per padded shape)
    solver.solve(pods)

    start = time.perf_counter()
    result = solver.solve(pods)
    elapsed = time.perf_counter() - start

    scheduled = result.pods_scheduled
    pods_per_sec = scheduled / elapsed if elapsed > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": f"pods/sec scheduled ({N_PODS} pods x {N_TYPES} instance types, TPU solver)",
                "value": round(pods_per_sec, 1),
                "unit": "pods/sec",
                "vs_baseline": round(pods_per_sec / 100.0, 2),
                "backend": backend,
            }
        )
    )


if __name__ == "__main__":
    main()
